"""Paged + optionally int8-quantized KV pool for the serving engine.

The slot pool (`serving/cache.py`) preallocates one `[max_len]` lane
per slot, so a replica's concurrency is bounded by WORST-CASE sequence
length even when most requests are short. This module carves the same
byte budget into fixed-size blocks instead (the paged-attention idea):

- device side: per layer, `cached_key`/`cached_value` become a shared
  `[num_blocks, block_size, kv_heads, head_dim]` pool plus a
  shape-static `[num_slots, max_blocks_per_slot]` `block_table` of
  block ids and a `[num_slots]` `cache_index` of physical cursors.
  `modeling_llama._update_paged_cache` scatters each decode step at
  `table[lane, idx // bs] * bs + idx % bs` and gathers the lane's
  blocks back into a contiguous virtual lane with `jnp.take` — pure
  gather/scatter, so XLA-CPU tier-1 runs it unchanged;
- host side: `BlockAllocator`, a plain free list. ALL allocation math
  (alloc/free/accounting) stays in Python on the scheduler thread —
  nothing here is ever traced (the fslint fixture
  `tests/analysis_fixtures/paged_cache_clean.py` pins that split);
- block 0 is the NULL block: never allocated, parked-on by every free
  lane's table row. Stray writes from inactive lanes land there and
  are never read back unmasked.

The int8 mode stores the pools as int8 with fp32 per-(token, head)
absmax scales (`cached_key_scale`/`cached_value_scale`, the
`ops/int8_matmul.py` quantize idiom) — 1 byte/element + one float per
head per token, ~3.7x more KV tokens in the same bytes — and
dequantizes inside the attention read. The same scale layout works for
the slot layout (`init_pool_cache(layout="slot", kv_dtype="int8")`).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from fengshen_tpu.ops.int8_matmul import quantize_kv

#: the reserved garbage block free lanes point at (never allocated)
NULL_BLOCK = 0


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """ceil(n_tokens / block_size): the engine's admission charge for a
    request footprint. The ONE place the rounding lives — a speculative
    engine must charge `bucket + max_new + gamma` tokens (the verify
    window over-scatters up to gamma rejected entries past the cursor,
    and those writes must land in blocks the lane owns, never in a
    neighbour's)."""
    return -(-int(n_tokens) // int(block_size))


class BlockAllocator:
    """Host-side free list over the paged KV pool.

    Deterministic allocation: lowest-id-first from a fresh pool, then
    LIFO reuse (most-recently-freed first — freed blocks go back on
    the tail). Double-free and foreign-id frees raise instead of
    silently corrupting the pool. Lives strictly on the scheduler
    thread — the traced decode only ever sees the resulting
    block-table rows as device arrays.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block {NULL_BLOCK} is the reserved "
                f"null block), got {num_blocks}")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))
        self._used: set[int] = set()

    @property
    def total_blocks(self) -> int:
        """Allocatable blocks (the null block is not one)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._used)

    def alloc(self, n: int) -> Optional[List[int]]:
        """`n` block ids, or None when the pool can't serve them all —
        the caller requeues the request (admission backpressure)."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._used.update(blocks)
        return blocks

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b not in self._used:
                raise ValueError(
                    f"free of block {b} that is not allocated "
                    "(double-free or foreign id)")
            self._used.remove(b)
            self._free.append(b)


def _map_attn_dicts(tree, fn):
    """Rebuild a cache pytree, applying `fn` to every attention-cache
    dict (the one holding `cached_key`). Works for scan and non-scan
    layouts alike — the structure is nested plain dicts either way."""
    if isinstance(tree, dict):
        if "cached_key" in tree:
            return fn(tree)
        return {k: _map_attn_dicts(v, fn) for k, v in tree.items()}
    return tree


def _zip_attn_dicts(pool, primed, fn):
    """Like `_map_attn_dicts` but walks the pool and a primed batch-1
    cache (which lacks the paged/scale leaves) in lockstep."""
    if isinstance(pool, dict):
        if "cached_key" in pool:
            return fn(pool, primed)
        return {k: _zip_attn_dicts(v, primed[k], fn) for k, v in
                pool.items()}
    return pool


def _vmap_layers(fn, lead: int):
    """Map a per-layer function over `lead` leading layer axes (0 for
    unrolled layers, 1 under scan_layers)."""
    for _ in range(lead):
        fn = jax.vmap(fn)
    return fn


def init_pool_cache(model, num_slots: int, *, layout: str = "slot",
                    kv_dtype: str = "fp32", num_blocks: int = 0,
                    block_size: int = 0, max_blocks_per_slot: int = 0):
    """Zeros KV pool for the engine — the one constructor for all four
    (layout, dtype) combinations. Abstract-init only, like
    `cache.init_slot_cache` (which this generalizes; the fp32 slot
    result is structurally identical to it)."""
    if layout not in ("slot", "paged"):
        raise ValueError(f"unknown kv layout {layout!r}")
    if kv_dtype not in ("fp32", "int8"):
        raise ValueError(f"unknown kv dtype {kv_dtype!r}")
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((num_slots, 1), jnp.int32),
                           init_cache=True))

    def build(d):
        ck = d["cached_key"]
        lead = ck.shape[:-4]                 # (layers,) under scan
        n_kv, head_dim = ck.shape[-2:]
        pool_dt = jnp.int8 if kv_dtype == "int8" else ck.dtype
        if layout == "paged":
            val_shape = lead + (num_blocks, block_size, n_kv, head_dim)
            scale_shape = lead + (num_blocks, block_size, n_kv)
        else:
            val_shape = lead + d["cached_key"].shape[-4:]
            scale_shape = lead + ck.shape[-4:-1]
        out = {
            "cached_key": jnp.zeros(val_shape, pool_dt),
            "cached_value": jnp.zeros(val_shape, pool_dt),
            "cache_index": jnp.zeros(lead + (num_slots,), jnp.int32),
        }
        if kv_dtype == "int8":
            out["cached_key_scale"] = jnp.zeros(scale_shape, jnp.float32)
            out["cached_value_scale"] = jnp.zeros(scale_shape,
                                                  jnp.float32)
        if layout == "paged":
            out["block_table"] = jnp.zeros(
                lead + (num_slots, max_blocks_per_slot), jnp.int32)
        return out
    return _map_attn_dicts(abstract["cache"], build)


def assign_slot_quantized(pool, primed, slot):
    """int8 flavor of `cache.assign_slot`: quantize the fp32 primed
    lane (the direct `_prefill_cache` output) per (token, head) while
    scattering it into int8 lane `slot`. `slot` may be traced."""
    def put(pool_d, prim_d):
        lead = pool_d["cached_key"].ndim - 4

        def vals(pool_leaf, prim_leaf, pick):
            def one(p, s):
                return jax.lax.dynamic_update_slice(
                    p, pick(quantize_kv(s[0]))[None], (slot,) +
                    (0,) * (p.ndim - 1))
            return _vmap_layers(one, lead)(pool_leaf, prim_leaf)

        out = dict(pool_d)
        out["cached_key"] = vals(pool_d["cached_key"],
                                 prim_d["cached_key"], lambda qs: qs[0])
        out["cached_value"] = vals(pool_d["cached_value"],
                                   prim_d["cached_value"],
                                   lambda qs: qs[0])
        out["cached_key_scale"] = vals(pool_d["cached_key_scale"],
                                       prim_d["cached_key"],
                                       lambda qs: qs[1])
        out["cached_value_scale"] = vals(pool_d["cached_value_scale"],
                                         prim_d["cached_value"],
                                         lambda qs: qs[1])
        out["cache_index"] = pool_d["cache_index"].at[..., slot].set(
            prim_d["cache_index"].astype(pool_d["cache_index"].dtype))
        return out
    return _zip_attn_dicts(pool, primed, put)


def assign_paged(pool, primed, slot, table_row):
    """Scatter a primed batch-1 cache into the blocks of `table_row`
    (a `[max_blocks_per_slot]` int32 vector from the host allocator,
    padded with the null block) and point lane `slot` at them.

    The first `max_blocks * block_size` tokens of the primed lane are
    copied wholesale — unpadded-row entries land in the lane's real
    blocks, padding entries clobber the null block (by design: garbage
    that is never read unmasked). One compiled program for every
    bucket, mirroring `assign_slot`. Quantizes on the way in when the
    pool is int8."""
    def put(pool_d, prim_d):
        ck = pool_d["cached_key"]
        lead = ck.ndim - 4
        num_blocks, block_size = ck.shape[-4:-2]
        max_blocks = pool_d["block_table"].shape[-1]
        virt_len = max_blocks * block_size
        int8 = "cached_key_scale" in pool_d
        positions = ((table_row * block_size)[:, None] +
                     jnp.arange(block_size)[None, :]).reshape(-1)

        def vals(pool_leaf, prim_leaf, pick):
            def one(p, s):
                src = s[0, :virt_len]            # [V, kv, hd] fp32
                val = pick(quantize_kv(src)) if int8 else \
                    src.astype(p.dtype)
                flat = p.reshape((num_blocks * block_size,) + p.shape[2:])
                return flat.at[positions].set(val).reshape(p.shape)
            return _vmap_layers(one, lead)(pool_leaf, prim_leaf)

        out = dict(pool_d)
        out["cached_key"] = vals(pool_d["cached_key"],
                                 prim_d["cached_key"], lambda qs: qs[0])
        out["cached_value"] = vals(pool_d["cached_value"],
                                   prim_d["cached_value"],
                                   lambda qs: qs[0])
        if int8:
            out["cached_key_scale"] = vals(pool_d["cached_key_scale"],
                                           prim_d["cached_key"],
                                           lambda qs: qs[1])
            out["cached_value_scale"] = vals(
                pool_d["cached_value_scale"], prim_d["cached_value"],
                lambda qs: qs[1])
        out["cache_index"] = pool_d["cache_index"].at[..., slot].set(
            prim_d["cache_index"].astype(pool_d["cache_index"].dtype))
        out["block_table"] = pool_d["block_table"].at[
            ..., slot, :].set(table_row)
        return out
    return _zip_attn_dicts(pool, primed, put)
