"""Continuous-batching inference engine: ONE jitted decode for all
in-flight requests.

`api/main.py`'s legacy path runs one pipeline call per POST — a decode
batch of 1, so concurrent users serialize behind each other and the
chip idles between dispatches. This engine multiplexes many requests
onto a fixed pool of `num_slots` KV-cache lanes:

- admission: queued prompts are LEFT-padded to a bucket
  (`buckets.BucketLadder`), prefilled batch-1 through the model's own
  cache machinery (`utils.generate._prefill_cache` — reused, not
  forked), and scattered into a free lane (`cache.assign_slot`);
- decode: every tick runs ONE jitted step over all `num_slots` lanes —
  per-lane `cache_index` vectors (modeling_llama's vector-index path)
  let lanes sit at different write positions, so the step never
  recompiles as requests come and go;
- reclaim: a finished/cancelled/expired lane is immediately handed to
  the next queued request — no drain barrier, no recompilation;
- backpressure: a bounded admission queue; `submit` raises `QueueFull`
  (HTTP 429 at the API layer) / `PromptTooLong` when the ladder can't
  hold the prompt;
- KV physicals: `kv_layout="paged"` swaps the per-lane pool for the
  block/paged pool (`serving/paged_cache.py`) — admission then charges
  each request its ACTUAL footprint in blocks instead of a worst-case
  lane, and an exhausted pool defers the queue head until reclaim;
  `kv_dtype="int8"` stores K/V quantized with per-(token, head) absmax
  scales. Both keep this module's one-jitted-decode contract.
- speculative tick: `spec_mode="prompt_lookup"` swaps the one-token
  decode for a draft→verify tick — an in-graph n-gram drafter proposes
  `spec_gamma` continuations per lane from the lane's on-device
  committed history, ONE jitted forward verifies all `[B, gamma+1]`
  positions through the same slot/paged cache, and per-lane accept
  counts (`utils.generate._spec_round_tokens`' greedy rule) advance
  each lane's cursor independently — decode is memory-bandwidth-bound,
  so committing >1 token per weight stream is the per-request latency
  lever the pool alone cannot pull. Works over both layouts and both
  kv dtypes; still exactly one decode program per engine.

Greedy decode is TOKEN-IDENTICAL to sequential
`utils.generate.generate` on the bucket-padded prompt (the parity test
pins it): same prefill, same logits controls
(`utils.generate.apply_logits_controls`), same selection — only the
physical cache layout is pooled.

Debug introspection (docs/serving.md "Debug endpoints"): every request
carries a host-side `RequestTimeline` of lifecycle events (enqueued,
admitted, prefill, per-tick commits incl. spec accept counts,
terminal), rendered as a latency waterfall by `debug_request()` /
`GET /debug/requests/<id>` and fed into
`fstpu_request_phase_seconds{phase}` at finish; a bounded ring keeps
the last `debug_ring` finished timelines. With a `recorder`
(`observability.FlightRecorder`) attached, the engine's event stream
enters the recorder's ring and a serve-loop tick error dumps a
post-mortem bundle (stats + config + the ring of timelines) before the
pool is rebuilt. All of it is host-side bookkeeping between jit
boundaries — the one-decode-compile contract and greedy token identity
are untouched (the timeline parity test pins both).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import (RequestTimeline,
                                        record_warmup_seconds, span)
from fengshen_tpu.ops.pallas import kernel_fingerprint, log_dispatch
from fengshen_tpu.serving.buckets import DEFAULT_BUCKETS, BucketLadder
from fengshen_tpu.serving.cache import (assign_slot, init_slot_cache,
                                        reset_free_slots, rollback_slots)
from fengshen_tpu.serving.paged_cache import (BlockAllocator,
                                              assign_paged,
                                              assign_slot_quantized,
                                              blocks_for_tokens,
                                              init_pool_cache)
from fengshen_tpu.serving.metrics import EngineMetrics
from fengshen_tpu.sharding import rules_fingerprint
from fengshen_tpu.streaming import StreamBook
from fengshen_tpu.utils.generate import (_controls_active,
                                         _ngram_propose_lanes,
                                         _prefill_cache, _select_token,
                                         _spec_round_tokens,
                                         _spec_round_tokens_lanes,
                                         apply_logits_controls)


class QueueFull(Exception):
    """Admission queue at `max_queue` — API layer maps this to 429."""


class PromptTooLong(Exception):
    """Prompt outgrows the bucket ladder or the cache headroom."""


class Draining(Exception):
    """Engine is draining (begin_drain): in-flight work finishes, new
    submissions are refused — API layer maps this to 503 with reason
    "draining" so a fleet router re-places the request."""


class DuplicateRequest(Exception):
    """An explicit request_id matching a live (queued/running) request.
    The replica-side half of the fleet router's idempotent-safe retry
    contract (docs/fleet.md): a retried id must never execute twice
    concurrently on one replica — API layer maps this to 409."""


# request lifecycle states
QUEUED, RUNNING, FINISHED, CANCELLED, EXPIRED, REJECTED = (
    "queued", "running", "finished", "cancelled", "expired", "rejected")


@dataclasses.dataclass
class EngineConfig:
    """Tuning knobs; see docs/serving.md for sizing guidance."""

    num_slots: int = 8
    buckets: Sequence[int] = DEFAULT_BUCKETS
    max_new_tokens: int = 128
    max_queue: int = 64
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 0.0
    repetition_penalty: float = 1.0
    no_repeat_ngram_size: int = 0   # 0 or 1 (see __post_init__)
    min_length: int = 0
    seed: int = 0
    # KV pool physicals (docs/serving.md "Paged KV cache"): "paged"
    # carves the pool into kv_block_size-token blocks so admission is
    # bounded by ACTUAL footprint (bucket + max_new), not worst-case
    # max_len; "int8" stores K/V quantized with per-(token, head)
    # scales — ~3.7x more KV tokens in the same bytes
    kv_layout: str = "slot"                  # "slot" | "paged"
    kv_dtype: str = "fp32"                   # "fp32" | "int8"
    kv_block_size: int = 64                  # tokens per paged block
    kv_num_blocks: Optional[int] = None      # default: slot-parity + null
    kv_max_blocks_per_slot: Optional[int] = None  # default: max_len/bs
    # speculative decode (docs/serving.md "Speculative decoding"):
    # "prompt_lookup" makes every tick draft spec_gamma tokens per lane
    # by n-gram match against the lane's on-device committed history
    # and verify all of them in ONE jitted forward — >1 committed token
    # per weight stream on repetitive/extractive text, greedy output
    # token-identical to the non-spec engine
    # "self_draft" swaps the n-gram drafter for a REAL draft tower: the
    # target's own first spec_draft_layers decoder layers (shared
    # embedding/norm/head, make_self_draft) run one batched draft pass
    # per tick — pays off on non-repetitive traffic where prompt
    # lookup's acceptance collapses, and carries a true proposal
    # distribution so sampled requests get the paper-exact
    # rejection-sampling accept rule per lane (docs/streaming.md)
    spec_mode: str = "off"    # "off" | "prompt_lookup" | "self_draft"
    spec_gamma: int = 4                      # drafted tokens per tick
    spec_ngram: int = 2                      # suffix length to match
    spec_draft_layers: int = 2               # self-draft tower depth
    # debug introspection (docs/serving.md "Debug endpoints"): how many
    # finished-request timelines the engine retains for
    # `GET /debug/requests` and the flight-recorder bundle
    debug_ring: int = 64
    # commit journal (docs/fault_tolerance.md "Preemption runbook"):
    # how many requests keep their committed-token journal entry for
    # `GET /partial/<id>` — the resume-from-token-k source a fleet
    # router consults before regenerating a maybe-executed retry
    journal_ring: int = 256

    def __post_init__(self):
        if self.num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if self.debug_ring < 1:
            raise ValueError("debug_ring must be >= 1")
        if self.journal_ring < 1:
            raise ValueError("journal_ring must be >= 1")
        if self.kv_layout not in ("slot", "paged"):
            raise ValueError(f"unknown kv_layout {self.kv_layout!r}; "
                             "expected 'slot' or 'paged'")
        if self.kv_dtype not in ("fp32", "int8"):
            raise ValueError(f"unknown kv_dtype {self.kv_dtype!r}; "
                             "expected 'fp32' or 'int8'")
        if self.kv_layout == "paged" and self.kv_block_size < 1:
            raise ValueError("kv_block_size must be >= 1")
        if self.max_queue < 1:
            # admission always passes through the queue, so 0 would
            # reject every request forever while all slots sit idle
            raise ValueError("max_queue must be >= 1")
        if self.no_repeat_ngram_size > 1:
            # the >1 processor slices history at a SCALAR cursor
            # (apply_logits_controls dynamic_slice); the pool decodes
            # every lane at a different cursor, so only the
            # ban-all-repeats size-1 form vectorizes
            raise ValueError(
                "the continuous engine supports no_repeat_ngram_size of "
                "0 or 1 only (per-slot cursors cannot drive the n>1 "
                "window processor)")
        if self.spec_mode not in ("off", "prompt_lookup", "self_draft"):
            raise ValueError(
                f"unknown spec_mode {self.spec_mode!r}; expected 'off', "
                "'prompt_lookup' or 'self_draft'")
        if self.spec_mode != "off":
            if self.spec_gamma < 1:
                raise ValueError("spec_gamma must be >= 1")
            if self.spec_ngram < 1:
                raise ValueError("spec_ngram must be >= 1")
            if self.spec_mode == "self_draft" and \
                    self.spec_draft_layers < 1:
                raise ValueError("spec_draft_layers must be >= 1")
            if self.do_sample and self.spec_mode == "prompt_lookup":
                # the rejection-sampling scheme needs the DRAFTER's
                # proposal distribution q; prompt lookup has none (its
                # proposals are copied tokens), so only greedy
                # accept-while-argmax-agrees is sound here. self_draft
                # DOES carry q — its sampled tick routes through the
                # per-lane rejection rule (_spec_round_tokens_lanes)
                raise ValueError(
                    "spec_mode='prompt_lookup' is greedy-only "
                    "(do_sample=False): lookup proposals carry no "
                    "draft distribution for the rejection-sampling "
                    "accept rule (use spec_mode='self_draft' for "
                    "sampled speculation)")
            if (self.repetition_penalty != 1.0 or
                    self.no_repeat_ngram_size > 0 or self.min_length > 0):
                # the processors are defined at ONE committed cursor;
                # the verify window scores gamma+1 cursors at once
                raise ValueError(
                    "spec_mode cannot run logits controls "
                    "(repetition_penalty / no_repeat_ngram_size / "
                    "min_length act per committed cursor, but the "
                    "verify forward scores gamma+1 positions at once)")


class Request:
    """One in-flight generation; host-side bookkeeping only."""

    _ids = itertools.count()

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 request_id: Optional[str], deadline: Optional[float],
                 submit_time: float, epoch: Optional[float] = None):
        self.request_id = request_id if request_id is not None else \
            f"req-{next(Request._ids)}"
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline            # engine-clock absolute time
        self.submit_time = submit_time
        self.state = QUEUED
        self.tokens: list[int] = []         # generated tokens (eos incl.)
        self.ttft_s: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.slot: Optional[int] = None
        #: resume-from-token-k (docs/fault_tolerance.md): tokens a
        #: previous execution already committed — prefilled as part of
        #: the prompt, never re-decoded — plus where they came from
        self.resume: list[int] = []
        self.resume_source: Optional[str] = None
        #: peer URL a live-evacuated lane moved to (handoff.py sets it)
        self.evac_target: Optional[str] = None
        #: per-request sampling seed (docs/streaming.md "Seed
        #: semantics"): folded into the engine's base key at admission
        #: to derive this lane's key ring entry; submit resolves it
        #: from the client field or the request-id hash
        self.seed: int = 0
        self._cancel = False
        self._done = threading.Event()
        #: host-side lifecycle events (docs/observability.md "Request
        #: tracing") — appended on the scheduler thread only, never
        #: inside traced code. `epoch` is the wall-clock anchor for
        #: `submit_time`'s monotonic axis (the engine's injectable
        #: wall clock) — what the fleet assembler's skew math reads.
        self.timeline = RequestTimeline(submit_time, epoch=epoch)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request leaves the engine (finished /
        cancelled / expired). True when it did within `timeout`."""
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


class ContinuousBatchingEngine:
    """Slot-pool continuous batching over one decoder-only model.

    `model` must use the repo's preallocated flax cache contract
    (cached_key/cached_value/cache_index — the LLaMA family). `clock`
    is injectable for deterministic deadline tests. `aot` is an
    optional `fengshen_tpu.aot.AotSetup`: when given, the prefill /
    assign / decode programs route through the persistent executable
    cache (`cached_compile`) instead of plain `jax.jit`, so a restarted
    replica deserializes yesterday's executables rather than re-paying
    XLA (docs/aot_cache.md).
    """

    #: dispatch discriminator for the API layer and /stats — the
    #: multimodal engines (serving/multimodal.py) carry their own
    engine_type = "continuous"

    def __init__(self, model: Any, params: Any, config: EngineConfig,
                 log: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 aot: Any = None, recorder: Any = None,
                 wall: Callable[[], float] = time.time):
        self.model = model
        self.params = params
        self.config = config
        self.ladder = BucketLadder(config.buckets)
        self.metrics = EngineMetrics()
        self._log = log or (lambda entry: None)
        self._clock = clock
        # wall-clock anchor for request timelines: pairs with the
        # injectable monotonic `clock` so the fleet assembler's
        # cross-process skew math is deterministic under test
        self._wall = wall
        # debug introspection state (docs/serving.md "Debug endpoints"):
        # a bounded ring of finished-request timelines, engine start
        # time for /stats uptime, and the last serve-loop error (type +
        # age only — never a traceback payload)
        self._recent: deque = deque(maxlen=config.debug_ring)
        self._t0_clock = clock()
        self._last_error: Optional[dict] = None
        self._recorder = recorder
        if recorder is not None:
            # engine events enter the recorder's ring on their way to
            # the caller's sink; the provider contributes stats/config/
            # timelines to every post-mortem bundle
            self._log = recorder.wrap_sink(self._log)
            recorder.attach("engine", self._debug_bundle)
        # THE loud kernel line (docs/kernels.md): state the dispatch
        # decision for every registered kernel once at startup and set
        # the fstpu_kernel_dispatch gauge — a fleet that silently
        # degraded to the xla lowering must be visible to a scraper
        log_dispatch(self._log)
        self.max_len = int(model.config.max_position_embeddings)
        self.paged = config.kv_layout == "paged"
        self.spec = config.spec_mode != "off"
        self.self_draft = config.spec_mode == "self_draft"
        # every admission must reserve gamma EXTRA positions: the
        # verify forward scatters the full gamma+1 window before the
        # accept counts are known, so rejected tails land past the
        # cursor (masked, later overwritten) but must stay inside the
        # lane (the engine analog of _check_spec_cache_headroom)
        self._gamma = config.spec_gamma if self.spec else 0
        S = config.num_slots
        if self.paged:
            bs = int(config.kv_block_size)
            if bs > self.max_len:
                raise ValueError(
                    f"kv_block_size {bs} exceeds "
                    f"max_position_embeddings={self.max_len}")
            mb = int(self.max_len // bs
                     if config.kv_max_blocks_per_slot is None
                     else config.kv_max_blocks_per_slot)
            if mb < 1 or mb * bs > self.max_len:
                raise ValueError(
                    f"kv_max_blocks_per_slot={mb} x kv_block_size={bs} "
                    f"must fit in 1..max_position_embeddings="
                    f"{self.max_len}")
            # explicit `is None` (not `or`): a computed kv_num_blocks of
            # 0 must fail loudly below, never silently balloon to the
            # slot-parity default pool
            nb = int(S * mb + 1 if config.kv_num_blocks is None
                     else config.kv_num_blocks)
            self.block_size, self.max_blocks_per_slot = bs, mb
            self.num_blocks = nb
            # the lane's logical extent: positions beyond it have no
            # block to land in, so it bounds prompt+decode like max_len
            # bounds the slot layout
            self.seq_capacity = mb * bs
            self._allocator = BlockAllocator(nb)
            self._slot_blocks: list[list[int]] = [[] for _ in range(S)]
            self._deferred_req: Optional[str] = None
        else:
            self.seq_capacity = self.max_len
        if self.ladder.buckets[0] + 1 + self._gamma > self.seq_capacity:
            raise ValueError(
                f"smallest bucket {self.ladder.buckets[0]} leaves no "
                f"decode headroom in the KV lane capacity "
                f"{self.seq_capacity}" +
                (f" (speculative window needs gamma={self._gamma} "
                 "extra positions)" if self._gamma else ""))

        if self.self_draft:
            # the self-draft tower (docs/streaming.md "Draft tower"):
            # the target's own first spec_draft_layers decoder layers
            # plus its shared embedding/norm/head — make_self_draft's
            # param leaves ALIAS the target's arrays, no copy. Its KV
            # pool is always a plain fp32 slot pool sized to this
            # engine's lane capacity (the tower is small, so paging or
            # quantizing it would save little and cost congruence with
            # the target cache's cursors).
            from fengshen_tpu.models.llama import make_self_draft
            draft_cfg, self._draft_params = make_self_draft(
                model.config, params, config.spec_draft_layers)
            if self.seq_capacity != self.max_len:
                draft_cfg = dataclasses.replace(
                    draft_cfg,
                    max_position_embeddings=self.seq_capacity)
            self._draft_model = model.clone(config=draft_cfg)
            self._draft_cache = init_slot_cache(self._draft_model, S)

        L = self.seq_capacity
        self._cache = self._init_pool()
        self._kv_bytes = sum(
            leaf.nbytes for path, leaf in
            jax.tree_util.tree_flatten_with_path(self._cache)[0]
            if any(getattr(k, "key", "").startswith("cached_")
                   for k in path))
        self._history = jnp.zeros((S, L), jnp.int32)
        self._mask = jnp.zeros((S, L), jnp.int32)
        # host-side per-slot state (authoritative for scheduling)
        self._last_tok = np.zeros((S,), np.int32)
        self._pos = np.zeros((S,), np.int32)    # logical position of last_tok
        self._phys = np.zeros((S,), np.int32)   # physical cache cursor
        self._active = np.zeros((S,), bool)
        self._slot_req: list[Optional[Request]] = [None] * S

        self._queue: deque[Request] = deque()
        # commit journal: request_id -> the live Request object, a
        # bounded insertion-ordered ring beside the debug ring. Entries
        # are references, so the committed-token list grows in place at
        # zero per-tick cost; `partial()` snapshots it for
        # `GET /partial/<id>` (docs/fault_tolerance.md)
        self._journal: "OrderedDict[str, Request]" = OrderedDict()
        #: live SSE token streams (docs/streaming.md): per-request
        #: bounded token queues the scheduler thread feeds at commit
        #: time; an engine that never streams pays one dict lookup of
        #: overhead per sync call and nothing else
        self.streams = StreamBook()
        self._draining = False
        self._cv = threading.Condition()
        self._base_key = jax.random.PRNGKey(config.seed)
        self._zero_key = jax.random.PRNGKey(0)
        # per-lane PRNG key ring beside cache_index (docs/streaming.md
        # "Seed semantics"): one key per lane, installed at admission
        # from fold_in(base_key, request.seed) and split IN-GRAPH every
        # tick — a lane's draws are a pure function of its seed and its
        # tick count since admission, never of pool co-tenancy
        self._keys = jnp.zeros((S,) + self._zero_key.shape,
                               self._zero_key.dtype)
        self._thread: Optional[threading.Thread] = None
        self._stop_flag = False

        cfg = config
        control_kw = dict(repetition_penalty=cfg.repetition_penalty,
                          no_repeat_ngram_size=cfg.no_repeat_ngram_size,
                          min_length=cfg.min_length,
                          eos_token_id=cfg.eos_token_id)
        controls_on = _controls_active(cfg.repetition_penalty,
                                       cfg.no_repeat_ngram_size,
                                       cfg.min_length)

        def prefill_fn(params, ids, mask, rng):
            # identical math to generate()'s prompt phase: mask-cumsum
            # positions, _prefill_cache, controls on the last position
            position_ids = jnp.clip(mask.cumsum(-1) - 1, 0, None)
            logits, cache = _prefill_cache(model, params, ids, mask,
                                           position_ids)
            step_logits = logits[:, -1]
            if controls_on:
                step_logits = apply_logits_controls(
                    step_logits, ids, jnp.int32(ids.shape[1]),
                    history_mask=mask, **control_kw)
            tok = _select_token(step_logits, rng, cfg.do_sample,
                                cfg.temperature, cfg.top_k, cfg.top_p)
            return cache, tok.astype(jnp.int32)

        if self.self_draft:
            # the draft tower primes its OWN cache over the same
            # prompt in the same program — its cursor starts congruent
            # with the target's and stays congruent tick over tick
            # (both advance gamma+1 and roll back gamma-n_r together)
            draft_model = self._draft_model
            base_prefill = prefill_fn

            def prefill_fn(params, draft_params, ids, mask, rng):
                cache, tok = base_prefill(params, ids, mask, rng)
                position_ids = jnp.clip(mask.cumsum(-1) - 1, 0, None)
                _, d_cache = _prefill_cache(draft_model, draft_params,
                                            ids, mask, position_ids)
                return cache, d_cache, tok

        paged = self.paged
        if paged:
            def assign_fn(cache, history, mask, primed, prompt_row,
                          mask_row, table_row, slot):
                cache = assign_paged(cache, primed, slot, table_row)
                history = history.at[slot].set(prompt_row)
                mask = mask.at[slot].set(mask_row)
                return cache, history, mask
        elif config.kv_dtype == "int8":
            def assign_fn(cache, history, mask, primed, prompt_row,
                          mask_row, slot):
                cache = assign_slot_quantized(cache, primed, slot)
                history = history.at[slot].set(prompt_row)
                mask = mask.at[slot].set(mask_row)
                return cache, history, mask
        else:
            def assign_fn(cache, history, mask, primed, prompt_row,
                          mask_row, slot):
                cache = assign_slot(cache, primed, slot)
                history = history.at[slot].set(prompt_row)
                mask = mask.at[slot].set(mask_row)
                return cache, history, mask

        if self.self_draft:
            # the draft pool is a plain slot pool regardless of the
            # target layout, so its lane assignment is always the
            # unquantized scatter
            base_assign = assign_fn
            if paged:
                def assign_fn(cache, dpool, history, mask, primed,
                              d_primed, prompt_row, mask_row, table_row,
                              slot):
                    cache, history, mask = base_assign(
                        cache, history, mask, primed, prompt_row,
                        mask_row, table_row, slot)
                    dpool = assign_slot(dpool, d_primed, slot)
                    return cache, dpool, history, mask
            else:
                def assign_fn(cache, dpool, history, mask, primed,
                              d_primed, prompt_row, mask_row, slot):
                    cache, history, mask = base_assign(
                        cache, history, mask, primed, prompt_row,
                        mask_row, slot)
                    dpool = assign_slot(dpool, d_primed, slot)
                    return cache, dpool, history, mask

        gamma, ngram = cfg.spec_gamma, cfg.spec_ngram
        if self.self_draft:
            draft_model = self._draft_model

            def decode_fn(params, draft_params, cache, dpool, history,
                          mask, tokens, pos, phys, active, keys):
                """Self-draft speculative tick: gamma+1 BATCHED draft
                forwards (a lax.scan over the small tower, all lanes at
                once) → ONE target verify over [B, gamma+1] → the
                paper-exact per-lane accept rule, sampled or greedy,
                keyed from the per-lane ring. Both caches advance and
                roll back together, so their cursors stay congruent."""
                n = tokens.shape[0]
                if paged:
                    cache = reset_free_slots(cache, active)
                dpool = reset_free_slots(dpool, active)
                if cfg.do_sample:
                    # gamma+3 splits per lane: next ring entry, gamma+1
                    # draft draws (the +1 is scanned but unused — keeps
                    # the scan xs rectangular), one verify key
                    split = jax.vmap(
                        lambda k: jax.random.split(k, gamma + 3))(keys)
                    keys_out = split[:, 0]
                    d_keys = jnp.moveaxis(split[:, 1:gamma + 2], 1, 0)
                    round_keys = split[:, gamma + 2]
                else:
                    keys_out = keys
                    d_keys = jnp.zeros((gamma + 1,) + keys.shape,
                                       keys.dtype)
                    round_keys = keys
                history = history.at[jnp.arange(n), phys].set(tokens)

                def draft_step(carry, xs):
                    dcache, cur = carry
                    i, dkey = xs
                    dlogits, dmut = draft_model.apply(
                        {"params": draft_params, "cache": dcache},
                        cur[:, None], attention_mask=mask,
                        position_ids=(pos + i)[:, None],
                        init_cache=True, mutable=["cache"])
                    step = dlogits[:, -1]
                    if cfg.do_sample:
                        # each proposal is an exact draw from the q
                        # that the accept rule divides by: same
                        # _filtered_logits, same temp/top-k/top-p
                        nxt = jax.vmap(
                            lambda l, k: _select_token(
                                l, k, True, cfg.temperature,
                                cfg.top_k, cfg.top_p))(step, dkey)
                    else:
                        nxt = step.astype(jnp.float32).argmax(-1)
                    nxt = nxt.astype(jnp.int32)
                    return (dmut["cache"], nxt), (nxt, step)

                # gamma+1 steps: the first feeds last tick's committed
                # token (writing its draft-KV at phys, mirroring the
                # target verify), the rest extend the proposal chain;
                # the last proposal is never verified, but its forward
                # writes the KV the NEXT tick's first step would need
                # anyway
                (dpool, _), (props, d_steps) = jax.lax.scan(
                    draft_step, (dpool, tokens),
                    (jnp.arange(gamma + 1), d_keys))
                drafts = jnp.transpose(props[:gamma])
                d_logits = jnp.moveaxis(d_steps[:gamma], 0, 1)
                verify = jnp.concatenate([tokens[:, None], drafts],
                                         axis=1)
                v_pos = pos[:, None] + jnp.arange(gamma + 1)[None]
                logits, mutated = model.apply(
                    {"params": params, "cache": cache}, verify,
                    attention_mask=mask, position_ids=v_pos,
                    init_cache=True, mutable=["cache"])
                n_r, w = _spec_round_tokens_lanes(
                    logits, d_logits, drafts, round_keys,
                    do_sample=cfg.do_sample,
                    temperature=cfg.temperature, top_k=cfg.top_k,
                    top_p=cfg.top_p)
                n_r = jnp.where(active, n_r, 0)
                delta = jnp.where(active, gamma - n_r, 0)
                # both cursors advanced gamma+1; both roll back the
                # rejected tail together (the draft pool too — its
                # stale entries past the cursor are masked, the
                # _rollback_cache invariant)
                cache = rollback_slots(mutated["cache"], delta)
                dpool = rollback_slots(dpool, delta)
                if not paged:
                    cache = reset_free_slots(cache, active)
                c = n_r + 1     # committed this tick (1..gamma+1)
                win = jnp.where(
                    jnp.arange(gamma + 1)[None] < c[:, None], w,
                    cfg.pad_token_id)
                win = jnp.where(active[:, None], win, cfg.pad_token_id)
                history = jax.vmap(
                    lambda row, wrow, p: jax.lax.dynamic_update_slice(
                        row, wrow, (p,)))(history, win, phys + 1)
                return cache, dpool, history, keys_out, n_r, win
        elif self.spec:
            def decode_fn(params, cache, history, mask, tokens, pos,
                          phys, active, keys):
                """Speculative tick: per-lane prompt-lookup draft → ONE
                verify forward over [B, gamma+1] → per-lane greedy
                accept/commit. Entirely in-graph: the committed-history
                ring already lives on device, so the drafter costs no
                host round-trip (the fslint fixture
                spec_decode_clean.py pins this path clean)."""
                n = tokens.shape[0]
                if paged:
                    cache = reset_free_slots(cache, active)
                # the token selected last tick enters the history at
                # its physical cursor BEFORE the forward, exactly like
                # the plain tick — the drafter then matches the
                # ngram-suffix ending at phys+1
                history = history.at[jnp.arange(n), phys].set(tokens)
                drafts = _ngram_propose_lanes(history, phys + 1, ngram,
                                              gamma, tokens)
                verify = jnp.concatenate([tokens[:, None], drafts],
                                         axis=1)
                v_pos = pos[:, None] + jnp.arange(gamma + 1)[None]
                logits, mutated = model.apply(
                    {"params": params, "cache": cache}, verify,
                    attention_mask=mask, position_ids=v_pos,
                    init_cache=True, mutable=["cache"])
                # greedy accept = longest draft==argmax prefix, w = the
                # per-position corrections: EXACTLY _spec_round_tokens'
                # rule, shared with speculative_generate (prompt-lookup
                # proposals come from no distribution, so the sampled
                # accept rule does not apply — greedy only, enforced by
                # __post_init__)
                n_r, w = _spec_round_tokens(logits, None, drafts, None,
                                            do_sample=False)
                n_r = jnp.where(active, n_r, 0)
                # the verify advanced every lane's cursor by gamma+1;
                # each lane rolls back its REJECTED tail independently
                # (no KV rewind needed: entries past the index are
                # masked and overwritten — the _rollback_cache
                # invariant, per-lane via rollback_slots)
                cache = rollback_slots(
                    mutated["cache"],
                    jnp.where(active, gamma - n_r, 0))
                if not paged:
                    cache = reset_free_slots(cache, active)
                c = n_r + 1     # committed this tick (1..gamma+1)
                win = jnp.where(
                    jnp.arange(gamma + 1)[None] < c[:, None], w,
                    cfg.pad_token_id)
                win = jnp.where(active[:, None], win, cfg.pad_token_id)
                # committed window tokens join the history ring at
                # phys+1.. so the next tick's drafter can match them;
                # the slot past the new cursor holds pad, like
                # _speculative_loop's buffer
                history = jax.vmap(
                    lambda row, wrow, p: jax.lax.dynamic_update_slice(
                        row, wrow, (p,)))(history, win, phys + 1)
                return cache, history, keys, n_r, win
        else:
            def decode_fn(params, cache, history, mask, tokens, pos,
                          phys, active, keys):
                n = tokens.shape[0]
                if paged:
                    # clamp BEFORE the forward: a reclaimed lane's
                    # blocks may already belong to another request, so
                    # its stray write must be parked on the null block
                    # first (the slot layout clamps after — each lane
                    # owns its space)
                    cache = reset_free_slots(cache, active)
                if cfg.do_sample:
                    # split IN-GRAPH: the ring entry advances once per
                    # tick whether or not this lane commits, so a
                    # lane's draw sequence depends only on (seed, tick
                    # count) — never on which other lanes are resident
                    split = jax.vmap(jax.random.split)(keys)
                    keys_out, tick_keys = split[:, 0], split[:, 1]
                else:
                    keys_out, tick_keys = keys, keys
                # the token selected last tick enters the history at
                # its physical cursor BEFORE the forward (its K/V are
                # written at the same position by the cache update)
                history = history.at[jnp.arange(n), phys].set(tokens)
                logits, mutated = model.apply(
                    {"params": params, "cache": cache}, tokens[:, None],
                    attention_mask=mask, position_ids=pos[:, None],
                    init_cache=True, mutable=["cache"])
                cache = mutated["cache"] if paged else \
                    reset_free_slots(mutated["cache"], active)
                step_logits = logits[:, -1]
                if controls_on:
                    step_logits = apply_logits_controls(
                        step_logits, history, (phys + 1)[:, None],
                        history_mask=mask, **control_kw)
                if cfg.do_sample:
                    nxt = jax.vmap(
                        lambda l, k: _select_token(
                            l, k, True, cfg.temperature, cfg.top_k,
                            cfg.top_p))(step_logits, tick_keys)
                else:
                    nxt = _select_token(step_logits, None, False,
                                        cfg.temperature, cfg.top_k,
                                        cfg.top_p)
                nxt = jnp.where(active, nxt, cfg.pad_token_id)
                return cache, history, keys_out, nxt.astype(jnp.int32)

        # one compile per bucket width / exactly one for decode — the
        # parity + compile-count tests pin this via _cache_size().
        # Donation keeps the pool cache in place across ticks (a
        # num_slots × max_len KV pool re-copied every tick would cost
        # more than the decode itself); every donated arg is reassigned
        # from the outputs wherever these are called.
        self._aot = aot
        # self-draft programs carry two extra donated buffers (the
        # draft pool in both, plus the draft params slot shifting the
        # argnums); the key ring is donated everywhere it is threaded
        if self.self_draft:
            assign_donate = (0, 1, 2, 3)
            decode_donate = (2, 3, 4, 10)
        else:
            assign_donate = (0, 1, 2)
            decode_donate = (1, 2, 8)
        if aot is not None:
            # everything the closures bake into the traced programs
            # beyond argument avals — gates trusted manifest replay
            # (docs/aot_cache.md): config drift must demote replay to
            # the verified lower-and-hash path. The kernel dispatch
            # table is part of that identity: a pallas-compiled decode
            # must never be replayed on an xla-dispatch process
            # (docs/kernels.md)
            # the active logical-axis rules table is part of that
            # identity too: the same model under a different rules
            # table lowers to differently-partitioned programs
            fp = (f"{model.config!r}::{config!r}"
                  f"::{kernel_fingerprint()}"
                  f"::{rules_fingerprint()}")
            if self.self_draft:
                # the draft tower's shape is baked into the traced
                # programs too — a manifest compiled at one draft depth
                # must never replay at another
                fp += f"::draft={self._draft_model.config!r}"
            self._prefill_jit = aot.wrap(prefill_fn, "serving/prefill",
                                         fingerprint_extra=fp)
            self._assign_jit = aot.wrap(assign_fn, "serving/assign",
                                        donate_argnums=assign_donate,
                                        fingerprint_extra=fp)
            self._decode_jit = aot.wrap(decode_fn, "serving/decode",
                                        donate_argnums=decode_donate,
                                        fingerprint_extra=fp)
        else:
            self._prefill_jit = jax.jit(prefill_fn)
            self._assign_jit = jax.jit(assign_fn,
                                       donate_argnums=assign_donate)
            self._decode_jit = jax.jit(decode_fn,
                                       donate_argnums=decode_donate)

    def _init_pool(self):
        """Zeros KV pool in the configured (layout, dtype)."""
        cfg = self.config
        if not self.paged and cfg.kv_dtype == "fp32":
            return init_slot_cache(self.model, cfg.num_slots)
        if self.paged:
            return init_pool_cache(
                self.model, cfg.num_slots, layout="paged",
                kv_dtype=cfg.kv_dtype, num_blocks=self.num_blocks,
                block_size=self.block_size,
                max_blocks_per_slot=self.max_blocks_per_slot)
        return init_pool_cache(self.model, cfg.num_slots, layout="slot",
                               kv_dtype=cfg.kv_dtype)

    # ---- submission side -------------------------------------------

    def _journal_add_locked(self, req: Request) -> None:
        """Enter `req` into the bounded commit journal (caller holds
        self._cv). A duplicate request_id replaces the older entry —
        the LATEST execution owns the id (a resumed retry must not
        answer `GET /partial/<id>` with its predecessor's snapshot)."""
        self._journal[req.request_id] = req
        self._journal.move_to_end(req.request_id)
        while len(self._journal) > self.config.journal_ring:
            self._journal.popitem(last=False)

    def _record_rejection_locked(self, req: Request, reason: str,
                                 **attrs) -> None:
        """The ONE rejection record: mark the request, stamp the
        terminal timeline event, and put its waterfall in the debug
        ring. Caller holds self._cv."""
        req.state = REJECTED
        req.finish_reason = reason
        req.timeline.add(self._clock(), "rejected", reason=reason,
                         **attrs)
        self._recent.append(self._request_dict(req))
        # a rejected request's stream (opened at submit, then e.g.
        # flushed by begin_drain) must close, not hang its reader
        self._sync_stream(req)

    def _reject_prompt(self, ids: np.ndarray, reason: str,
                       request_id: Optional[str],
                       trace_id: Optional[str] = None,
                       parent_span_id: Optional[str] = None,
                       **attrs) -> None:
        """413-class rejections happen before a Request enters the
        queue, but their timelines still belong in the debug ring — a
        burst of 413s must be diagnosable from `GET /debug/requests`
        and the post-mortem bundle, like the 429s are."""
        req = Request(ids, 0, request_id, None, self._clock(),
                      epoch=self._wall())
        req.timeline.trace_id = trace_id
        req.timeline.parent_span_id = parent_span_id
        with self._cv:
            self._record_rejection_locked(
                req, reason, prompt_tokens=int(len(ids)), **attrs)

    def submit(self, input_ids, max_new_tokens: Optional[int] = None,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               parent_span_id: Optional[str] = None,
               resume_tokens: Optional[Sequence[int]] = None,
               resume_source: Optional[str] = None,
               seed: Optional[int] = None,
               stream: bool = False) -> Request:
        """Queue a prompt. Raises QueueFull (backpressure) or
        PromptTooLong (no bucket / no cache headroom). `deadline_s` is
        seconds from now; an expired request frees its slot and
        finishes with reason "deadline". `trace_id`/`parent_span_id`
        are the distributed-trace correlation ids carried in off the
        wire (docs/observability.md "Distributed tracing") — pure
        host-side bookkeeping stamped onto the request's timeline and
        debug-ring entry, never an input to any traced program.

        `resume_tokens` is the resume-from-token-k path
        (docs/fault_tolerance.md "Preemption runbook"): tokens a
        previous execution of this request already committed (read from
        a replica's `GET /partial/<id>` journal). Admission prefills
        prompt + resume_tokens[:-1] in ONE bucketed prefill — greedy
        left-padded prefill logits are position-for-position identical
        to incremental decode, so the remainder of the generation is
        token-identical to the undisturbed run — and only the remaining
        max_new - k tokens are decoded. `max_new_tokens` keeps its
        TOTAL-generation meaning (the resumed prefix counts toward it).

        `seed` pins this request's sampling stream (docs/streaming.md
        "Seed semantics"): the same prompt + seed reproduces the same
        sampled tokens run-to-run regardless of pool co-tenancy. When
        None, the seed derives from the request id, so an explicit-id
        retry replays the same stream. `stream=True` opens a live
        token stream the scheduler feeds at commit time
        (`Engine.streams` / docs/streaming.md).
        """
        if self._draining:
            # checked again under the lock below; this early exit just
            # spares rejected requests the bucket/blocks math
            self.metrics.count("rejected_draining")
            self._log({"event": "serving_reject", "reason": "draining"})
            raise Draining("engine is draining; not admitting")
        if max_new_tokens is not None and int(max_new_tokens) < 1:
            # a bad request field, not a too-long prompt — the API
            # layer maps this to 422, not 413
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        resume = [int(t) for t in resume_tokens] if resume_tokens \
            else []
        # resume on a speculative engine is sound: the max_new clamp is
        # gamma-aware, the paged footprint charge includes the gamma
        # tail, and both drafters read only the committed history —
        # which admission restores inside the prefill bucket. (This
        # gate used to reject; streaming retries made spec+resume the
        # common path, docs/streaming.md "Retry and resume".)
        requested_new = int(max_new_tokens if max_new_tokens is not None
                            else self.config.max_new_tokens)
        if resume and requested_new <= len(resume):
            # the journal already holds the whole generation — the
            # caller should have answered from it, not resubmitted
            raise ValueError(
                f"resume_tokens carries {len(resume)} tokens but "
                f"max_new_tokens={requested_new} leaves nothing to "
                "decode")
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        # a resumed request prefills prompt + resume[:-1] (the last
        # committed token re-enters as the decode seed, exactly where
        # an undisturbed lane would hold it)
        prefill_len = len(ids) + max(len(resume) - 1, 0)
        bucket = self.ladder.bucket_for(prefill_len)
        if bucket is None:
            self.metrics.count("rejected_prompt_too_long")
            self._log({"event": "serving_reject", "reason":
                       "prompt_too_long", "prompt_tokens": len(ids)})
            self._reject_prompt(ids, "prompt_too_long", request_id,
                                trace_id=trace_id,
                                parent_span_id=parent_span_id)
            raise PromptTooLong(
                f"prompt of {len(ids)} tokens exceeds the largest "
                f"bucket {self.ladder.max_bucket}")
        max_new = requested_new
        # the lane must hold bucket + generated tokens + the gamma-wide
        # speculative tail (seq_capacity is max_len for the slot
        # layout, blocks x block_size for paged); clamping without the
        # gamma term would let the verify window silently walk past
        # the lane end — the off-by-gamma the boundary test pins.
        # A resumed request only DECODES max_new - (k-1) of its total:
        # k-1 committed tokens ride inside the prefill bucket, so they
        # restore that much headroom to the clamp
        max_new = min(max_new, self.seq_capacity - bucket - self._gamma
                      + max(len(resume) - 1, 0))
        if max_new < (len(resume) + 1 if resume else 1):
            self.metrics.count("rejected_prompt_too_long")
            self._log({"event": "serving_reject", "reason":
                       "prompt_too_long", "prompt_tokens": len(ids)})
            self._reject_prompt(ids, "prompt_too_long", request_id,
                                trace_id=trace_id,
                                parent_span_id=parent_span_id,
                                bucket=int(bucket))
            raise PromptTooLong(
                f"bucket {bucket} leaves no decode headroom in the "
                f"KV lane capacity {self.seq_capacity}" +
                (f" (speculative window needs gamma={self._gamma} "
                 "extra positions)" if self._gamma else ""))
        # tokens the lane actually DECODES past the prefill bucket —
        # what the paged footprint is charged for (a resumed request's
        # committed prefix lives inside the bucket)
        decode_span = max_new - len(resume) + 1 if resume else max_new
        if self.paged:
            # a footprint the whole pool cannot hold would sit at the
            # queue head forever (nothing can free enough blocks) —
            # reject NOW instead of livelocking the FIFO
            need = blocks_for_tokens(bucket + decode_span + self._gamma,
                                     self.block_size)
            if need > self._allocator.total_blocks:
                self.metrics.count("rejected_prompt_too_long")
                self._log({"event": "serving_reject",
                           "reason": "kv_pool_too_small",
                           "prompt_tokens": len(ids),
                           "blocks_needed": need,
                           "blocks_total":
                               self._allocator.total_blocks})
                self._reject_prompt(
                    ids, "kv_pool_too_small", request_id,
                    trace_id=trace_id, parent_span_id=parent_span_id,
                    blocks_needed=int(need),
                    blocks_total=int(self._allocator.total_blocks))
                raise PromptTooLong(
                    f"request needs {need} KV blocks but the pool "
                    f"only has {self._allocator.total_blocks}")
        now = self._clock()
        req = Request(ids, max_new, request_id,
                      None if deadline_s is None else now + deadline_s,
                      now, epoch=self._wall())
        req.timeline.trace_id = trace_id
        req.timeline.parent_span_id = parent_span_id
        # resolve the per-request sampling seed: an explicit client
        # seed wins; otherwise hash the request id, so an explicit-id
        # retry (the router's resume path) folds to the SAME lane key
        # and the resumed stream continues the same distribution
        req.seed = (int(seed) & 0x7FFFFFFF) if seed is not None \
            else zlib.crc32(req.request_id.encode()) & 0x7FFFFFFF
        if resume:
            # seed the committed prefix NOW: the journal and the debug
            # endpoints must show the true progress from the first
            # moment, and the finish check counts TOTAL generation
            req.resume = resume
            req.resume_source = resume_source
            req.tokens = list(resume)
        with span("serving/admit"), self._cv:
            if self._draining:
                self.metrics.count("rejected_draining")
                self._log({"event": "serving_reject",
                           "reason": "draining"})
                raise Draining("engine is draining; not admitting")
            if request_id is not None:
                # idempotent-safe retry contract (docs/fleet.md): an
                # explicit id may never run twice concurrently here —
                # a router retrying a request this replica may still
                # be executing must be REJECTED, not doubled. (No
                # debug-ring entry: the ORIGINAL request owns the id
                # there; the counter + log line carry the 409s.)
                for live in list(self._queue) + [
                        r for r in self._slot_req if r is not None]:
                    if live.request_id == request_id:
                        self.metrics.count("rejected_duplicate")
                        self._log({"event": "serving_reject",
                                   "reason": "duplicate_request_id",
                                   "request_id": request_id,
                                   "live_state": live.state})
                        raise DuplicateRequest(
                            f"request_id {request_id!r} is already "
                            f"{live.state} on this replica")
            if len(self._queue) >= self.config.max_queue:
                self.metrics.count("rejected_queue_full")
                self._log({"event": "serving_reject",
                           "reason": "queue_full",
                           "queue_depth": len(self._queue)})
                # rejected timelines join the debug ring: "who was 429'd
                # and when" is exactly the overload question
                self._record_rejection_locked(
                    req, "queue_full", queue_depth=len(self._queue))
                raise QueueFull(
                    f"admission queue at max_queue="
                    f"{self.config.max_queue}")
            self._queue.append(req)
            req.timeline.add(now, "enqueued",
                             prompt_tokens=int(len(ids)), bucket=bucket,
                             queue_depth=len(self._queue))
            if resume:
                # the initial resume mark (the `evacuated` event's
                # cross-replica counterpart): where the committed
                # prefix came from and how long it is
                req.timeline.add(now, "resumed_from",
                                 tokens=len(resume),
                                 source=resume_source)
            self._journal_add_locked(req)
            if stream:
                # open the live stream BEFORE any token can commit so
                # the reader never misses the head; open() replays
                # req.tokens, so a resumed stream starts at k, not 0
                self.streams.open(req)
            self.metrics.count("admitted")
            self._log({"event": "serving_admit",
                       "request_id": req.request_id, "bucket": bucket,
                       "queue_depth": len(self._queue)})
            self._cv.notify_all()
        return req

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued or running request; a running one frees its
        slot at the next tick. False when the id is unknown/done."""
        with self._cv:
            for req in self._queue:
                if req.request_id == request_id:
                    self._queue.remove(req)
                    self._finish(req, CANCELLED, "cancelled")
                    return True
            for req in self._slot_req:
                if req is not None and req.request_id == request_id:
                    req._cancel = True
                    return True
        return False

    # ---- engine loop -----------------------------------------------

    def step(self) -> int:
        """One tick: reclaim → admit → one jitted decode over the pool.
        Returns the number of lanes still active after the tick."""
        with self._cv:
            # the tick IS the critical section: the scheduler owns all
            # device state under _cv by design; admission threads wait
            # at most one tick (docs/serving.md "Threading")
            return self._step_locked()  # fslint: disable=blocking-under-lock; deliberate scheduler design

    def _step_locked(self) -> int:
        now = self._clock()
        # a queued request whose deadline already passed will never be
        # worth prefilling — drop it while it waits, not just at pop
        expired = [r for r in self._queue
                   if r.deadline is not None and now > r.deadline]
        for req in expired:
            self._queue.remove(req)
            self._finish(req, EXPIRED, "deadline")
        for i, req in enumerate(self._slot_req):
            if req is None:
                continue
            if req._cancel:
                self._release(i, CANCELLED, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                self._release(i, EXPIRED, "deadline")
        self._admit()
        active_idx = np.nonzero(self._active)[0]
        if len(active_idx) == 0:
            return 0
        t0 = time.perf_counter()
        if self.spec:
            with span("serving/decode"):
                if self.self_draft:
                    (self._cache, self._draft_cache, self._history,
                     self._keys, n_r, win) = self._decode_jit(
                        self.params, self._draft_params, self._cache,
                        self._draft_cache, self._history, self._mask,
                        self._last_tok, self._pos, self._phys,
                        self._active, self._keys)
                else:
                    (self._cache, self._history, self._keys, n_r,
                     win) = self._decode_jit(
                        self.params, self._cache, self._history,
                        self._mask, self._last_tok, self._pos,
                        self._phys, self._active, self._keys)
                # host sync: the scheduler needs the accept counts and
                # the committed window (copies — the device views are
                # read-only and lanes are overwritten on admission)
                n_r = np.array(n_r)
                win = np.array(win)
            dt = time.perf_counter() - t0
            # per-lane commit: accepted prefix + the correction token,
            # so each lane's cursor advances INDEPENDENTLY (the whole
            # point over generate's batched min-advance)
            commit = np.where(self._active, n_r + 1, 0)
            last = win[np.arange(win.shape[0]),
                       np.maximum(commit - 1, 0)]
            self._last_tok = np.where(self._active, last,
                                      self.config.pad_token_id
                                      ).astype(np.int32)
            self._pos = (self._pos + commit).astype(np.int32)
            self._phys = (self._phys + commit).astype(np.int32)
            # metrics count DELIVERED tokens, not the raw window: a
            # lane finishing mid-window (eos, or the max_new cap)
            # discards the tail, and counting it would inflate
            # decode_tokens and the acceptance rate the bench's
            # committed-per-forward headline is derived from
            delivered = 0
            accepted_delivered = 0
            t_commit = self._clock()
            for i in active_idx:
                req = self._slot_req[i]
                k = 0
                fin = None
                for tok in (int(t) for t in win[i, :commit[i]]):
                    req.tokens.append(tok)
                    k += 1
                    if self.config.eos_token_id is not None and \
                            tok == self.config.eos_token_id:
                        fin = "eos"
                        break
                    if len(req.tokens) >= req.max_new_tokens:
                        fin = "length"
                        break
                # the commit event must precede a release: _finish
                # snapshots the timeline into the debug ring
                req.timeline.add(t_commit, "commit", n=k,
                                 accepted=min(int(n_r[i]), k),
                                 tick_s=round(dt, 6))
                self._sync_stream(req)
                if fin is not None:
                    self._release(i, FINISHED, fin)
                delivered += k
                # delivered tokens at offsets < n_r are accepted
                # drafts; the one at offset n_r is the correction
                accepted_delivered += min(int(n_r[i]), k)
            self.metrics.record_tick(len(active_idx),
                                     self.config.num_slots, dt,
                                     tokens=delivered)
            self.metrics.record_spec(
                self.config.spec_gamma * len(active_idx),
                accepted_delivered)
            return int(self._active.sum())
        with span("serving/decode"):
            self._cache, self._history, self._keys, nxt = \
                self._decode_jit(
                    self.params, self._cache, self._history, self._mask,
                    self._last_tok, self._pos, self._phys, self._active,
                    self._keys)
            # host sync: the scheduler needs the tokens (copy — the
            # device view is read-only and lanes are overwritten on
            # admission)
            nxt = np.array(nxt)
        dt = time.perf_counter() - t0
        self.metrics.record_tick(len(active_idx), self.config.num_slots,
                                 dt)
        self._last_tok = nxt
        self._pos[self._active] += 1
        self._phys[self._active] += 1
        t_commit = self._clock()
        for i in active_idx:
            req = self._slot_req[i]
            tok = int(nxt[i])
            req.tokens.append(tok)
            req.timeline.add(t_commit, "commit", n=1,
                             tick_s=round(dt, 6))
            self._sync_stream(req)
            if self.config.eos_token_id is not None and \
                    tok == self.config.eos_token_id:
                self._release(i, FINISHED, "eos")
            elif len(req.tokens) >= req.max_new_tokens:
                self._release(i, FINISHED, "length")
        return int(self._active.sum())

    def _admit(self) -> None:
        for slot in range(self.config.num_slots):
            if self._active[slot] or not self._queue:
                continue
            req = self._queue.popleft()
            now = self._clock()
            if req._cancel:
                self._finish(req, CANCELLED, "cancelled")
                continue
            if req.deadline is not None and now > req.deadline:
                self._finish(req, EXPIRED, "deadline")
                continue
            # resume-from-token-k admission (docs/fault_tolerance.md):
            # the committed prefix minus its last token joins the
            # prompt in ONE bucketed prefill — identical left-pad
            # cumsum positions make the combined prefill's KV
            # position-for-position equal to the incremental decode
            # that produced those tokens, which is what keeps the
            # remainder greedy token-identical to the unkilled run
            resume = req.resume
            prefill_ids = req.prompt if not resume else np.concatenate(
                [req.prompt, np.asarray(resume[:-1], np.int32)])
            bucket = self.ladder.bucket_for(len(prefill_ids))
            decode_span = req.max_new_tokens - len(resume) + 1 \
                if resume else req.max_new_tokens
            blocks = None
            if self.paged:
                # admission switches from "free slot" to "enough free
                # blocks" for the request's ACTUAL footprint; when the
                # pool can't serve it, the head of the queue waits for
                # reclaim (FIFO — later requests must not starve it),
                # the queue fills, and submit's QueueFull (429) is the
                # backpressure surface
                need = blocks_for_tokens(
                    bucket + decode_span + self._gamma,
                    self.block_size)
                blocks = self._allocator.alloc(need)
                if blocks is None:
                    self._queue.appendleft(req)
                    if self._deferred_req != req.request_id:
                        # count the deferral EVENT once, not once per
                        # tick the head keeps waiting
                        self._deferred_req = req.request_id
                        self.metrics.count("deferred_admissions")
                        req.timeline.add(
                            now, "deferred", blocks_needed=int(need),
                            blocks_free=int(self._allocator.free_blocks))
                        self._log({"event": "serving_defer",
                                   "reason": "kv_blocks_exhausted",
                                   "request_id": req.request_id,
                                   "blocks_needed": need,
                                   "blocks_free":
                                       self._allocator.free_blocks})
                    return
                self._deferred_req = None
            try:
                row, mask_row = self.ladder.pad_prompt(
                    prefill_ids, bucket, self.config.pad_token_id)
                if self.config.do_sample:
                    # per-request key derivation (docs/streaming.md
                    # "Seed semantics"): fold the request seed into the
                    # engine base key, then split once — one half seeds
                    # the prefill draw, the other becomes this lane's
                    # ring entry. No global RNG is consumed, so a
                    # request's stream is independent of admission
                    # order and pool co-tenancy.
                    base = jax.random.fold_in(self._base_key, req.seed)
                    key, lane_key = jax.random.split(base)
                else:
                    key = lane_key = self._zero_key
                req.timeline.add(self._clock(), "admitted", slot=slot,
                                 bucket=int(bucket))
                req.timeline.add(self._clock(), "prefill_start",
                                 bucket=int(bucket))
                with span("serving/prefill"):
                    if self.self_draft:
                        primed, d_primed, tok = self._prefill_jit(
                            self.params, self._draft_params, row[None],
                            mask_row[None], key)
                    else:
                        primed, tok = self._prefill_jit(
                            self.params, row[None], mask_row[None], key)
                    tok = int(np.asarray(tok)[0])
                self.metrics.record_prefill(bucket)
                t_first = self._clock()
                req.ttft_s = t_first - req.submit_time
                self.metrics.record_ttft(req.ttft_s)
                req.timeline.add(t_first, "first_token")
                if resume:
                    # the prefill-selected token is DISCARDED: a
                    # resumed lane's next decode seed is the
                    # already-committed resume[-1] (seeded into
                    # req.tokens at submit), not a re-selection —
                    # exactly the cursor the unkilled lane would hold
                    tok = resume[-1]
                else:
                    req.tokens.append(tok)
                self._sync_stream(req)
                if self.config.eos_token_id is not None and \
                        tok == self.config.eos_token_id:
                    if blocks is not None:
                        self._allocator.free(blocks)
                        blocks = None
                    self._finish(req, FINISHED, "eos")
                    continue
                if len(req.tokens) >= req.max_new_tokens:
                    if blocks is not None:
                        self._allocator.free(blocks)
                        blocks = None
                    self._finish(req, FINISHED, "length")
                    continue
                # history/mask lanes: padded prompt, mask open from
                # the bucket edge on (causal validity bounds the open
                # tail)
                L = self.seq_capacity
                hist_row = np.zeros((L,), np.int32)
                hist_row[:bucket] = row
                full_mask = np.ones((L,), np.int32)
                full_mask[:bucket] = mask_row
                if self.paged:
                    table_row = np.zeros((self.max_blocks_per_slot,),
                                         np.int32)
                    table_row[:len(blocks)] = blocks
            except BaseException:  # noqa: BLE001 — release + re-raise
                # a failed prefill must not strand the request's KV
                # blocks: return them to the pool before propagating
                if blocks is not None:
                    self._allocator.free(blocks)
                raise
            if self.self_draft:
                if self.paged:
                    self._slot_blocks[slot] = blocks
                    (self._cache, self._draft_cache, self._history,
                     self._mask) = self._assign_jit(
                        self._cache, self._draft_cache, self._history,
                        self._mask, primed, d_primed, hist_row,
                        full_mask, table_row, np.int32(slot))
                else:
                    (self._cache, self._draft_cache, self._history,
                     self._mask) = self._assign_jit(
                        self._cache, self._draft_cache, self._history,
                        self._mask, primed, d_primed, hist_row,
                        full_mask, np.int32(slot))
            elif self.paged:
                self._slot_blocks[slot] = blocks
                self._cache, self._history, self._mask = \
                    self._assign_jit(self._cache, self._history,
                                     self._mask, primed, hist_row,
                                     full_mask, table_row,
                                     np.int32(slot))
            else:
                self._cache, self._history, self._mask = \
                    self._assign_jit(self._cache, self._history,
                                     self._mask, primed, hist_row,
                                     full_mask, np.int32(slot))
            req.state = RUNNING
            req.slot = slot
            self._slot_req[slot] = req
            self._active[slot] = True
            self._last_tok[slot] = tok
            # logical pos of last_tok: len(prompt) for a fresh lane
            # (tokens == [tok]); a resumed lane holds k committed
            # tokens, the same invariant pos = P + len(tokens) - 1
            self._pos[slot] = len(req.prompt) + len(req.tokens) - 1
            self._phys[slot] = bucket           # physical cursor
            if self.config.do_sample:
                # install the lane's ring entry; greedy engines keep
                # the zero ring and never consume it
                self._keys = self._keys.at[slot].set(lane_key)
        return

    def _release(self, slot: int, state: str, reason: str) -> None:
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        self._active[slot] = False
        self._phys[slot] = 0
        self._pos[slot] = 0
        if self.paged and self._slot_blocks[slot]:
            # blocks return to the free list NOW; the lane's stale
            # block-table row is parked on the null block by the next
            # decode's entry clamp before any write can land
            self._allocator.free(self._slot_blocks[slot])
            self._slot_blocks[slot] = []
        self._finish(req, state, reason)

    def _finish(self, req: Request, state: str, reason: str) -> None:
        req.state = state
        req.finish_reason = reason
        req.slot = None
        if state == FINISHED:
            self.metrics.count("completed")
        elif state == CANCELLED:
            self.metrics.count("cancelled")
        elif state == EXPIRED:
            self.metrics.count("expired")
        end_t = self._clock()
        req.timeline.add(end_t, state, reason=reason)
        phases = req.timeline.phases(end_t)
        self.metrics.record_phases(phases)
        self._recent.append(self._request_dict(req, phases=phases))
        self.metrics.record_latency(end_t - req.submit_time)
        self._log({"event": "serving_finish",
                   "request_id": req.request_id, "reason": reason,
                   "tokens": len(req.tokens), "ttft_s": req.ttft_s})
        # terminal stream sync: finish_reason is set, so the stream
        # (if open) delivers any tail tokens and closes
        self._sync_stream(req)
        req._done.set()

    def _sync_stream(self, req: Request) -> None:
        """Push `req`'s committed tokens to its live stream, if one is
        open. O(1) dict probe when it is not — the cost a non-streaming
        engine pays per commit. Host-side only, never traced."""
        n = self.streams.sync(req)
        if n:
            self.metrics.record_stream_tokens(n)

    # ---- drivers ----------------------------------------------------

    def run_until_idle(self, max_ticks: int = 1_000_000) -> None:
        """Offline driver: tick until queue and pool are empty."""
        for _ in range(max_ticks):
            with self._cv:
                if not self._queue and not self._active.any():
                    return
                self._step_locked()  # fslint: disable=blocking-under-lock; offline driver, same tick-owns-lock design as step()
        raise RuntimeError(f"engine still busy after {max_ticks} ticks")

    def generate_all(self, prompts,
                     max_new_tokens: Optional[int] = None) -> list:
        """Submit every prompt, drain, return per-prompt token lists."""
        reqs = [self.submit(p, max_new_tokens) for p in prompts]
        self.run_until_idle()
        return [r.tokens for r in reqs]

    def start(self) -> None:
        """Serve in a daemon thread (the API layer's mode): ticks run
        whenever work exists, sleep on the condition var otherwise."""
        if self._thread is not None:
            return
        self._stop_flag = False
        self._thread = threading.Thread(target=self._serve_loop,
                                        daemon=True)
        self._thread.start()

    def _serve_loop(self) -> None:
        while not self._stop_flag:
            try:
                n = self.step()
            except Exception as e:  # noqa: BLE001 — a dead serve
                # thread would leave every waiter blocked for its full
                # timeout and the server accepting traffic against a
                # wedged engine; fail the in-flight work loudly and
                # keep serving (the tick may have died mid-donation,
                # so the pool is rebuilt from scratch)
                self._log({"event": "serving_tick_error",
                           "error": str(e)[:500]})
                with self._cv:
                    # /stats surfaces type + age only — the full text
                    # already went to the log line above, and a
                    # traceback has no place in a polled JSON payload
                    self._last_error = {"type": type(e).__name__,
                                        "at": self._clock()}
                    self._reset_pool_locked()
                if self._recorder is not None:
                    # the reset above finished the in-flight requests,
                    # so their timelines are already in the debug ring
                    # the bundle snapshots; dump failures must not
                    # re-kill the loop the except arm just saved
                    try:
                        self._recorder.snapshot_metrics(
                            (self.metrics.registry,), force=True)
                        self._recorder.dump(
                            reason="engine_tick_error",
                            extra={"error_type": type(e).__name__})
                    except Exception as dump_err:  # noqa: BLE001
                        self._log({"event": "flightrec_dump_error",
                                   "error": str(dump_err)[:200]})
                n = 0
            if self._recorder is not None:
                # periodic ring snapshot (rate-limited inside): the
                # post-mortem bundle carries recent metric trajectories,
                # not just the final values
                self._recorder.snapshot_metrics((self.metrics.registry,))
            if n == 0:
                with self._cv:
                    if not self._queue and not self._stop_flag:
                        self._cv.wait(timeout=0.02)

    def _reset_pool_locked(self) -> None:
        """Fail every queued/running request and rebuild the slot pool
        (donated buffers may be invalid after a mid-tick error)."""
        for req in list(self._queue):
            self._queue.remove(req)
            self._finish(req, EXPIRED, "engine_error")
        for i, req in enumerate(self._slot_req):
            if req is not None:
                self._release(i, EXPIRED, "engine_error")
        S, L = self.config.num_slots, self.seq_capacity
        if self.paged:
            self._allocator = BlockAllocator(self.num_blocks)
            self._slot_blocks = [[] for _ in range(S)]
            self._deferred_req = None
        self._cache = self._init_pool()
        self._history = jnp.zeros((S, L), jnp.int32)
        self._mask = jnp.zeros((S, L), jnp.int32)
        self._keys = jnp.zeros((S,) + self._zero_key.shape,
                               self._zero_key.dtype)
        if self.self_draft:
            self._draft_cache = init_slot_cache(self._draft_model, S)
        self._last_tok = np.zeros((S,), np.int32)
        self._pos = np.zeros((S,), np.int32)
        self._phys = np.zeros((S,), np.int32)
        self._active = np.zeros((S,), bool)

    def stop(self) -> None:
        self._stop_flag = True
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ---- drain (docs/fleet.md "Drain runbook") ----------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting (submit raises `Draining`) and FLUSH the
        queued-but-unstarted requests back to their callers as orderly
        rejections (reason "draining" → 503 at the API layer, so a
        fleet router re-places them NOW instead of letting them wait
        out the drain timeout). Running lanes keep decoding — they are
        the live-evacuation candidates (docs/fault_tolerance.md
        "Preemption runbook"). `/stats` flips `draining` to true so a
        fleet router's poll routes around this replica even before the
        API layer's healthz does."""
        with self._cv:
            if self._draining:
                return
            self._draining = True
            flushed = list(self._queue)
            self._queue.clear()
            for req in flushed:
                # the terminal "rejected" event + ring entry; _done
                # wakes the API thread blocked in request.wait() so the
                # 503 goes out immediately (no engine counter: the
                # rejected_draining count is pinned to SUBMIT refusals)
                self._record_rejection_locked(req, "draining")
                req._done.set()
            self._log({"event": "serving_drain",
                       "queued_flushed": len(flushed),
                       "active": int(self._active.sum())})
            self._cv.notify_all()

    def idle(self) -> bool:
        """True when nothing is queued or decoding (the drain handler's
        exit condition)."""
        with self._cv:
            return not self._queue and not bool(self._active.any())

    def live_lane_ids(self) -> list:
        """Request ids of every RUNNING lane — the drain handler's
        evacuation worklist (disagg.coordinator.evacuate_all)."""
        with self._cv:
            return [r.request_id for r in self._slot_req
                    if r is not None and r.state == RUNNING]

    # ---- commit journal (docs/fault_tolerance.md) -------------------

    def partial(self, request_id: str) -> Optional[dict]:
        """`GET /partial/<id>`: the committed-token journal entry a
        fleet router consults before regenerating a maybe-executed
        retry from token 0. None when the id never ran here or aged
        out of the journal ring. The token list is a SNAPSHOT under
        the engine lock — a live lane keeps committing after it."""
        with self._cv:
            req = self._journal.get(request_id)
            if req is None:
                return None
            out = {"request_id": req.request_id,
                   "state": req.state,
                   "finish_reason": req.finish_reason,
                   "prompt_tokens": int(len(req.prompt)),
                   "generated_tokens": len(req.tokens),
                   "tokens": [int(t) for t in req.tokens],
                   "max_new_tokens": int(req.max_new_tokens),
                   "ttft_s": (None if req.ttft_s is None
                              else round(req.ttft_s, 6)),
                   "trace_id": req.timeline.trace_id}
            if req.evac_target is not None:
                out["evac_target"] = req.evac_target
            if req.resume:
                out["resumed_tokens"] = len(req.resume)
                out["resume_source"] = req.resume_source
            return out

    def attach_stream(self, request_id: str):
        """(Re)open the live token stream of a journaled request — the
        `Last-Event-ID` reconnect path (docs/streaming.md "Reconnect").
        Idempotent: a stream already open is returned as-is; a request
        that already finished yields a stream that replays its tokens
        and closes immediately. None when the id never ran here or
        aged out of the journal ring."""
        with self._cv:
            req = self._journal.get(request_id)
            if req is None:
                return None
            return self.streams.open(req)

    # ---- observability ----------------------------------------------

    def warmup(self) -> float:
        """Compile every prefill bucket + the decode step before traffic
        (the first user must not pay jit). Returns seconds.

        With an AOT setup attached, the warmup manifest is replayed
        first — thread-parallel, hitting the persistent executable
        cache when warm (docs/aot_cache.md) — and covers `serving/
        assign` too (which plain warmup only compiles at the first
        admission); the loop below then finds every program already
        built and is reduced to shape bookkeeping."""
        t0 = time.perf_counter()
        replay = None
        if self._aot is not None:
            replay = self._aot.replay({
                "serving/prefill": self._prefill_jit,
                "serving/assign": self._assign_jit,
                "serving/decode": self._decode_jit})
            if replay is not None:
                record_warmup_seconds("aot_replay", replay["seconds"])
        if self._aot is not None:
            # AOT path: `warm()` builds (compiles or deserializes) each
            # program WITHOUT executing it — after a manifest replay
            # these are instant signature hits; on a cold/stale cache
            # they compile exactly what the loop below would have
            with self._cv:
                for bucket in self.ladder.buckets:
                    if bucket + 1 > self.seq_capacity:
                        continue
                    ids = np.ones((1, bucket), np.int32)
                    mask = np.ones((1, bucket), np.int32)
                    if self.self_draft:
                        self._prefill_jit.warm(
                            self.params, self._draft_params, ids, mask,
                            self._zero_key)
                    else:
                        self._prefill_jit.warm(self.params, ids, mask,
                                               self._zero_key)
                if self.self_draft:
                    self._decode_jit.warm(
                        self.params, self._draft_params, self._cache,
                        self._draft_cache, self._history, self._mask,
                        self._last_tok, self._pos, self._phys,
                        self._active, self._keys)
                else:
                    self._decode_jit.warm(
                        self.params, self._cache, self._history,
                        self._mask, self._last_tok, self._pos,
                        self._phys, self._active, self._keys)
        else:
            with self._cv:
                for bucket in self.ladder.buckets:
                    if bucket + 1 > self.seq_capacity:
                        continue
                    ids = np.ones((1, bucket), np.int32)
                    mask = np.ones((1, bucket), np.int32)
                    # warmup compiles under _cv on purpose: no request
                    # may tick mid-warmup or it would pay (and double-
                    # compile) the very programs being primed
                    if self.self_draft:
                        jax.block_until_ready(self._prefill_jit(  # fslint: disable=blocking-under-lock; warmup must exclude ticks
                            self.params, self._draft_params, ids, mask,
                            self._zero_key))
                    else:
                        jax.block_until_ready(self._prefill_jit(  # fslint: disable=blocking-under-lock; warmup must exclude ticks
                            self.params, ids, mask, self._zero_key))
                # cache/history/keys (and the draft pool) are donated,
                # so reassign them; with every lane free the warmup
                # tick is a no-op on pool state (free lanes write at
                # index 0 and are fully overwritten by the next
                # assignment anyway) and on the zero key ring
                if self.self_draft:
                    out = self._decode_jit(  # fslint: disable=blocking-under-lock; warmup must exclude ticks
                        self.params, self._draft_params, self._cache,
                        self._draft_cache, self._history, self._mask,
                        self._last_tok, self._pos, self._phys,
                        self._active, self._keys)
                    (self._cache, self._draft_cache, self._history,
                     self._keys) = out[0], out[1], out[2], out[3]
                else:
                    out = self._decode_jit(  # fslint: disable=blocking-under-lock; warmup must exclude ticks
                        self.params, self._cache, self._history,
                        self._mask, self._last_tok, self._pos,
                        self._phys, self._active, self._keys)
                    self._cache, self._history, self._keys = \
                        out[0], out[1], out[2]
                jax.block_until_ready(self._cache)  # fslint: disable=blocking-under-lock; warmup must exclude ticks
        dt = time.perf_counter() - t0
        self.metrics.warmup_compile_s = round(dt, 3)
        record_warmup_seconds("engine", dt)
        entry = {"event": "serving_warmup", "seconds": round(dt, 3),
                 "buckets": list(self.ladder.buckets),
                 "num_slots": self.config.num_slots}
        if replay is not None:
            entry["aot_replayed"] = replay["replayed"]
        self._log(entry)
        return dt

    def _kv_stats_locked(self) -> dict:
        """KV-pool utilization for `/stats` + the `fstpu_kv_*` gauges.
        The slot layout reports lanes as max_len-token blocks so the
        two layouts read on one scale; fragmentation is the unwritten
        fraction of ALLOCATED lane capacity (bucket padding counts as
        written — those positions hold real, masked K/V)."""
        cfg = self.config
        used_tokens = int(self._phys[self._active].sum())
        if self.paged:
            total = self._allocator.total_blocks
            used = self._allocator.used_blocks
            block_tokens = self.block_size
            alloc_tokens = sum(len(b) for b in self._slot_blocks) * \
                block_tokens
        else:
            total = cfg.num_slots
            used = int(self._active.sum())
            block_tokens = self.max_len
            alloc_tokens = used * block_tokens
        frag = round(1.0 - used_tokens / alloc_tokens, 4) \
            if alloc_tokens else 0.0
        return {
            "layout": cfg.kv_layout, "dtype": cfg.kv_dtype,
            "blocks_total": total, "blocks_used": used,
            "blocks_free": total - used, "block_tokens": block_tokens,
            "bytes": self._kv_bytes, "fragmentation": frag,
        }

    def stats(self) -> dict:
        with self._cv:
            now = self._clock()
            last_error = None
            if self._last_error is not None:
                last_error = {
                    "type": self._last_error["type"],
                    "age_s": round(now - self._last_error["at"], 3)}
            # engine_type EXTENDS the pinned payload (same precedent
            # as uptime_s/draining): the fleet router and benchdiff
            # key multimodal-vs-text comparisons on it
            return dict(self.metrics.snapshot(
                queue_depth=len(self._queue),
                slots_active=int(self._active.sum()),
                num_slots=self.config.num_slots,
                kv=self._kv_stats_locked(),
                # None keeps the non-spec payload byte-identical to
                # the pre-spec /stats shape (pinned by tests)
                spec=({"mode": self.config.spec_mode,
                       "gamma": self.config.spec_gamma}
                      if self.spec else None),
                # same pattern for streams: an engine that never
                # streamed keeps the exact pre-streaming payload shape
                streams=({"active": self.streams.active()}
                         if self.streams.ever_opened else None),
                uptime_s=now - self._t0_clock,
                last_error=last_error,
                draining=self._draining), engine_type=self.engine_type)

    # ---- debug introspection (docs/serving.md "Debug endpoints") ----

    def _request_dict(self, req: Request,
                      phases: Optional[dict] = None) -> dict:
        """Full waterfall payload for one request (live or finished).
        Callers hold self._cv (every mutation site does)."""
        if phases is None:
            phases = req.timeline.phases(self._clock())
        d = {"request_id": req.request_id,
             "state": req.state,
             "finish_reason": req.finish_reason,
             "prompt_tokens": int(len(req.prompt)),
             "generated_tokens": len(req.tokens),
             "max_new_tokens": int(req.max_new_tokens),
             "slot": req.slot,
             "ttft_s": (None if req.ttft_s is None
                        else round(req.ttft_s, 6)),
             "phases": phases}
        d.update(req.timeline.to_dict())
        return d

    @staticmethod
    def _request_summary(d: dict) -> dict:
        """The list-endpoint row: the waterfall minus its event log.
        trace_id rides along so a fleet trace can be followed from the
        list without fetching every full timeline."""
        return {k: d[k] for k in
                ("request_id", "state", "finish_reason",
                 "prompt_tokens", "generated_tokens", "slot",
                 "ttft_s", "phases", "trace_id")}

    def _live_summary_locked(self, req: Request) -> dict:
        """Summary for a LIVE request without materializing its event
        list — debug_requests holds the engine lock, so the scheduler
        must not stall behind event serialization on every scrape."""
        return {"request_id": req.request_id, "state": req.state,
                "finish_reason": req.finish_reason,
                "prompt_tokens": int(len(req.prompt)),
                "generated_tokens": len(req.tokens),
                "slot": req.slot,
                "ttft_s": (None if req.ttft_s is None
                           else round(req.ttft_s, 6)),
                "phases": req.timeline.phases(self._clock()),
                "trace_id": req.timeline.trace_id}

    def _live_requests_locked(self) -> list:
        return list(self._queue) + [r for r in self._slot_req
                                    if r is not None]

    def debug_requests(self) -> dict:
        """`GET /debug/requests`: summaries of every queued + running
        request plus the bounded ring of recently finished (or
        rejected) timelines, newest last."""
        with self._cv:
            in_flight = [self._live_summary_locked(r)
                         for r in self._live_requests_locked()]
            recent = [self._request_summary(d) for d in self._recent]
        return {"in_flight": in_flight, "recent": recent,
                "debug_ring": self.config.debug_ring}

    def debug_request(self, request_id: str) -> Optional[dict]:
        """`GET /debug/requests/<id>`: the full event timeline +
        derived waterfall; None when the id is neither live nor in the
        ring (it aged out or never existed)."""
        with self._cv:
            for req in self._live_requests_locked():
                if req.request_id == request_id:
                    return self._request_dict(req)
            for d in reversed(self._recent):
                if d["request_id"] == request_id:
                    return d
        return None

    def _debug_bundle(self) -> dict:
        """The flight-recorder provider: everything a post-mortem needs
        to answer "what was the engine doing" (docs/observability.md
        "Flight recorder"). Runs on the dumping thread with no engine
        lock held across the whole bundle — stats() and
        debug_requests() each take it briefly."""
        with self._cv:
            requests = [self._request_dict(r)
                        for r in self._live_requests_locked()]
            requests += list(self._recent)
        return {"stats": self.stats(),
                "engine_config": repr(self.config),
                "model_config": repr(self.model.config),
                "requests": requests}
