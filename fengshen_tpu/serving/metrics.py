"""Engine-level serving metrics.

Same conventions as the resilience subsystem (resilience/loader.py): the
engine takes an optional `log` callable and emits one small dict per
event (`serving_admit`, `serving_reject`, `serving_finish`,
`serving_warmup`) so a Trainer-style metrics.jsonl — or any structured
logger — can ingest them; `snapshot()` is the `/stats` endpoint payload.
"""

from __future__ import annotations

import threading
from collections import deque


def _percentile(values, q: float) -> float:
    vals = sorted(values)
    if not vals:
        return 0.0
    idx = min(int(q * len(vals)), len(vals) - 1)
    return float(vals[idx])


class EngineMetrics:
    """Thread-safe counters + bounded windows for the serving engine."""

    def __init__(self, window: int = 512):
        self._lock = threading.Lock()
        self.admitted = 0
        self.rejected_queue_full = 0
        self.rejected_prompt_too_long = 0
        self.completed = 0
        self.cancelled = 0
        self.expired = 0
        self.prefills = {}          # bucket -> count
        self.decode_ticks = 0
        self.decode_tokens = 0
        self.decode_time_s = 0.0
        self.occupied_slot_ticks = 0
        self.total_slot_ticks = 0
        self.warmup_compile_s = None
        self._ttft = deque(maxlen=window)
        self._latency = deque(maxlen=window)

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def record_prefill(self, bucket: int) -> None:
        with self._lock:
            self.prefills[bucket] = self.prefills.get(bucket, 0) + 1

    def record_tick(self, n_active: int, num_slots: int,
                    seconds: float) -> None:
        with self._lock:
            self.decode_ticks += 1
            self.decode_tokens += n_active
            self.decode_time_s += seconds
            self.occupied_slot_ticks += n_active
            self.total_slot_ticks += num_slots

    def record_ttft(self, seconds: float) -> None:
        with self._lock:
            self._ttft.append(seconds)

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self._latency.append(seconds)

    def snapshot(self, queue_depth: int, slots_active: int,
                 num_slots: int) -> dict:
        with self._lock:
            ttft = list(self._ttft)
            decode_tps = (self.decode_tokens / self.decode_time_s
                          if self.decode_time_s > 0 else 0.0)
            occupancy = (self.occupied_slot_ticks / self.total_slot_ticks
                         if self.total_slot_ticks > 0 else 0.0)
            return {
                "queue_depth": queue_depth,
                "slots_active": slots_active,
                "num_slots": num_slots,
                "admitted": self.admitted,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_prompt_too_long": self.rejected_prompt_too_long,
                "completed": self.completed,
                "cancelled": self.cancelled,
                "expired": self.expired,
                "prefills_per_bucket": dict(self.prefills),
                "decode_ticks": self.decode_ticks,
                "decode_tokens": self.decode_tokens,
                "decode_tokens_per_sec": round(decode_tps, 2),
                "slot_occupancy": round(occupancy, 4),
                "ttft_avg_s": round(sum(ttft) / len(ttft), 4) if ttft
                              else 0.0,
                "ttft_p50_s": round(_percentile(ttft, 0.5), 4),
                "ttft_p95_s": round(_percentile(ttft, 0.95), 4),
                "warmup_compile_s": self.warmup_compile_s,
            }
