"""Offline serving throughput microbench: continuous batching vs the
legacy one-request-at-a-time path.

Runs entirely offline (no HTTP) on whatever backend JAX picks — the
`make serve-bench` target pins CPU so the number is reproducible in CI
and BENCH rounds can track it without a healthy relay. Prints ONE JSON
line in the BENCH schema ({"metric", "value", "unit", "vs_baseline"},
value = engine tokens/s, vs_baseline = speedup over sequential) plus
ttft and config echo keys.

    make serve-bench
    SERVE_BENCH_NEW_TOKENS=128 python -m fengshen_tpu.serving.bench

Env knobs (SERVE_BENCH_*): SLOTS, REQUESTS, NEW_TOKENS, VOCAB, HIDDEN,
INTER, LAYERS, HEADS, BUCKETS (comma list), SEED.

Why batching wins even here: batch-1 decode is weight-memory-bound —
every generated token streams the full weight matrices for ONE row.
The slot pool streams them once per tick for `num_slots` rows, so
aggregate tokens/s scales with occupancy until compute saturates
(PAPERS.md: "Dissecting the Runtime Performance …" — batched decode is
the dominant inference-throughput lever).
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _env(name: str, default: int) -> int:
    return int(os.environ.get(f"SERVE_BENCH_{name}", default))


def main() -> None:
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.serving import ContinuousBatchingEngine, EngineConfig
    from fengshen_tpu.utils.generate import generate

    slots = _env("SLOTS", 8)
    n_req = _env("REQUESTS", 8)
    new_tokens = _env("NEW_TOKENS", 48)
    buckets = tuple(int(b) for b in os.environ.get(
        "SERVE_BENCH_BUCKETS", "32,64").split(","))
    # default shape sits in the weight-memory-bound decode regime (the
    # 300M-bench hidden/intermediate at 4 layers): batch-1 GEMV and
    # batch-8 GEMM stream the same weights, so the slot pool's batching
    # win is visible even on the CPU backend — tiny hidden sizes are
    # elementwise/dispatch-bound and hide it
    config = LlamaConfig(
        vocab_size=_env("VOCAB", 4096),
        hidden_size=_env("HIDDEN", 1024),
        intermediate_size=_env("INTER", 2816),
        num_hidden_layers=_env("LAYERS", 4),
        num_attention_heads=_env("HEADS", 8),
        max_position_embeddings=buckets[-1] + new_tokens,
        dtype="float32")
    model = LlamaForCausalLM(config)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(_env("SEED", 0)))

    rng = np.random.RandomState(_env("SEED", 0))
    span = max(buckets[-1] - 11, 1)  # varied lengths, any ladder size
    lengths = [min(buckets[-1], 12 + (i * 7) % span)
               for i in range(n_req)]
    prompts = [rng.randint(3, config.vocab_size - 1, n).astype(np.int32)
               for n in lengths]

    # ---- sequential baseline: one jitted generate per request --------
    # (exactly the legacy api/main.py path: each POST runs a batch-1
    # pipeline call; jit compile excluded via per-shape warmup)
    @jax.jit
    def _gen(params, ids):
        return generate(model, params, ids, max_new_tokens=new_tokens,
                        eos_token_id=None, pad_token_id=0)

    for n in sorted(set(lengths)):
        jax.block_until_ready(_gen(params, jnp.ones((1, n), jnp.int32)))
    t0 = time.perf_counter()
    for p in prompts:
        jax.block_until_ready(_gen(params, jnp.asarray(p)[None]))
    seq_dt = time.perf_counter() - t0
    seq_tps = n_req * new_tokens / seq_dt

    # ---- continuous engine: all requests in flight together ----------
    engine = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=slots, buckets=buckets,
                                    max_new_tokens=new_tokens,
                                    max_queue=max(n_req, 1),
                                    eos_token_id=None, pad_token_id=0))
    engine.warmup()
    t0 = time.perf_counter()
    outs = engine.generate_all(prompts)
    eng_dt = time.perf_counter() - t0
    generated = sum(len(t) for t in outs)
    eng_tps = generated / eng_dt
    stats = engine.stats()

    row = {
        "metric": "serving_engine_tokens_per_sec",
        "value": round(eng_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(eng_tps / seq_tps, 3),
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "ttft_avg_s": stats["ttft_avg_s"],
        "ttft_p95_s": stats["ttft_p95_s"],
        "slot_occupancy": stats["slot_occupancy"],
        "requests": n_req,
        "num_slots": slots,
        "new_tokens": new_tokens,
        "backend": jax.default_backend(),
    }
    # utilization column (docs/observability.md): forward-only FLOPs —
    # decode does no backward; present whenever the estimator supports
    # the benched model (it does: llama-shaped config)
    from fengshen_tpu.observability import (JsonlSink,
                                            estimate_flops_per_token,
                                            peak_flops_per_chip)
    f_tok = estimate_flops_per_token(config, include_backward=False)
    if f_tok:
        peak = peak_flops_per_chip(jax.devices()[0].device_kind)
        row["mfu"] = float(f"{eng_tps * f_tok / (peak * len(jax.devices())):.4g}")
    if os.environ.get("BENCH_DEGRADED", "0") == "1":
        row["degraded"] = True
    JsonlSink(stream=sys.stdout, only_process_zero=False)(row)


if __name__ == "__main__":
    main()
