"""Offline serving throughput microbench: continuous batching vs the
legacy one-request-at-a-time path.

Runs entirely offline (no HTTP) on whatever backend JAX picks — the
`make serve-bench` target pins CPU so the number is reproducible in CI
and BENCH rounds can track it without a healthy relay. Prints ONE JSON
line in the BENCH schema ({"metric", "value", "unit", "vs_baseline"},
value = engine tokens/s, vs_baseline = speedup over sequential) plus
ttft and config echo keys.

    make serve-bench
    SERVE_BENCH_NEW_TOKENS=128 python -m fengshen_tpu.serving.bench

`SERVE_BENCH_MODE=memory_parity` (`make serve-bench-parity`) switches
to the KV **memory-parity** comparison (docs/performance.md): the slot
pool's byte budget is held FIXED and re-carved as paged fp32 and
paged+int8 pools; each variant reports the max concurrent requests it
admitted and its aggregate tokens/s. The paged pool admits by ACTUAL
footprint (bucket + max_new blocks) instead of worst-case max_len
lanes, and int8 stores ~3-4x more KV tokens per byte, so `value` /
`vs_baseline` become the paged-over-slot concurrency ratio (the >= 2x
acceptance bar of ISSUE 6).

`SERVE_BENCH_MODE=spec` (`make serve-bench-spec`) benches the
**speculative decode tick** (docs/serving.md "Speculative decoding"):
the same engine/workload with `spec_mode="off"` vs `"prompt_lookup"`.
The workload is repetitive TEXT by construction — a random-init model
has no real language to copy, so the bench probes candidate tokens
with one short batched generate and keeps the ones whose greedy
continuations are the most self-repetitive (the synthetic stand-in
for the extractive/summarisation regime where prompt lookup pays).
`value` = committed tokens per target forward (1 + gamma x
acceptance_rate; the non-spec tick is exactly 1.0), `vs_baseline` the
same ratio; the row also carries `acceptance_rate`, both engines'
tokens/s, and `token_identical` (greedy spec output must equal the
non-spec engine's).

`SERVE_BENCH_MODE=multimodal` (`make serve-bench-multimodal`) benches
the **micro-batch multimodal engines** (docs/serving.md "Multimodal
engines") on the small-test towers: one row per engine type
(`batch_image`, `embedding`), each carrying `engine_type`; `value` =
engine requests/s with all requests co-arriving, `vs_baseline` the
speedup over sequential one-per-call pipeline invocations.

Env knobs (SERVE_BENCH_*): SLOTS, REQUESTS, NEW_TOKENS, VOCAB, HIDDEN,
INTER, LAYERS, HEADS, BUCKETS (comma list), SEED, MODE, BLOCK_SIZE,
MAX_SLOTS (paged concurrency cap in parity mode), SPEC_GAMMA,
SPEC_NGRAM, PROBE (spec-workload candidate count), MAX_BATCH
(multimodal micro-batch width).

Why batching wins even here: batch-1 decode is weight-memory-bound —
every generated token streams the full weight matrices for ONE row.
The slot pool streams them once per tick for `num_slots` rows, so
aggregate tokens/s scales with occupancy until compute saturates
(PAPERS.md: "Dissecting the Runtime Performance …" — batched decode is
the dominant inference-throughput lever).
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _env(name: str, default: int) -> int:
    return int(os.environ.get(f"SERVE_BENCH_{name}", default))


def _emit(row: dict) -> None:
    from fengshen_tpu.observability import JsonlSink
    if os.environ.get("BENCH_DEGRADED", "0") == "1":
        row["degraded"] = True
    JsonlSink(stream=sys.stdout, only_process_zero=False)(row)


def _sequential_tps(model, params, prompts, new_tokens: int) -> float:
    """The legacy api path: one jitted batch-1 generate per request
    (compiles excluded via per-shape warmup)."""
    from fengshen_tpu.utils.generate import generate

    @jax.jit
    def _gen(params, ids):
        return generate(model, params, ids, max_new_tokens=new_tokens,
                        eos_token_id=None, pad_token_id=0)

    for n in sorted({len(p) for p in prompts}):
        jax.block_until_ready(_gen(params, jnp.ones((1, n), jnp.int32)))
    t0 = time.perf_counter()
    for p in prompts:
        jax.block_until_ready(_gen(params, jnp.asarray(p)[None]))
    return len(prompts) * new_tokens / (time.perf_counter() - t0)


def _run_engine(model, params, prompts, cfg) -> dict:
    """Warm up, drain `prompts`, return throughput + pool stats."""
    from fengshen_tpu.serving import ContinuousBatchingEngine
    engine = ContinuousBatchingEngine(model, params, cfg)
    engine.warmup()
    t0 = time.perf_counter()
    outs = engine.generate_all(prompts)
    dt = time.perf_counter() - t0
    stats = engine.stats()
    return {"tokens_per_sec": round(sum(len(t) for t in outs) / dt, 1),
            "stats": stats, "outputs": outs}


def _memory_parity(model, params, config, buckets, new_tokens) -> None:
    """Same KV byte budget, three carvings: slot fp32 (the reference),
    paged fp32, paged int8. Deterministic concurrency: every variant
    gets enough requests and slots to hit its admission bound."""
    from fengshen_tpu.serving import EngineConfig

    slots_ref = _env("SLOTS", 8)
    block = _env("BLOCK_SIZE", 16)
    slot_cap = _env("MAX_SLOTS", 32)
    max_len = buckets[-1] + new_tokens
    kv = config.num_key_value_heads
    hd = config.head_dim
    layers = config.num_hidden_layers
    budget = slots_ref * max_len * kv * hd * 2 * 4 * layers

    # all requests land in the SMALLEST bucket — the realistic skew the
    # paged pool exploits (the ladder still serves the big bucket; the
    # slot pool pays its worst case for every lane regardless)
    prompt_len = max(buckets[0] // 2, 1)
    bucket = buckets[0]
    need_tokens = bucket + new_tokens
    need_blocks = -(-need_tokens // block)

    def blocks_for(budget_bytes: int, int8: bool) -> int:
        per_tok = kv * hd * 2 * (1 if int8 else 4) * layers
        if int8:
            per_tok += kv * 2 * 4 * layers        # absmax scales
        return budget_bytes // (block * per_tok)

    variants = {
        "slot": dict(num_slots=slots_ref),
        "paged": dict(kv_layout="paged", kv_block_size=block,
                      kv_num_blocks=blocks_for(budget, False)),
        "paged_int8": dict(kv_layout="paged", kv_dtype="int8",
                           kv_block_size=block,
                           kv_num_blocks=blocks_for(budget, True)),
    }
    bounds = {"slot": slots_ref}
    for name in ("paged", "paged_int8"):
        nb = variants[name]["kv_num_blocks"]
        bound = max((nb - 1) // need_blocks, 1)
        bounds[name] = min(bound, slot_cap)
        variants[name]["num_slots"] = bounds[name]

    n_req = max(_env("REQUESTS", 0), max(bounds.values()) + 2)
    rng = np.random.RandomState(_env("SEED", 0))
    prompts = [rng.randint(3, config.vocab_size - 1,
                           prompt_len).astype(np.int32)
               for _ in range(n_req)]
    seq_tps = _sequential_tps(model, params,
                              prompts[:min(n_req, 8)], new_tokens)

    results = {}
    for name, overrides in variants.items():
        cfg = EngineConfig(buckets=buckets, max_new_tokens=new_tokens,
                           max_queue=n_req, eos_token_id=None,
                           pad_token_id=0, **overrides)
        run = _run_engine(model, params, prompts, cfg)
        st = run["stats"]
        results[name] = {
            "max_concurrent": st["slots_active_peak"],
            "tokens_per_sec": run["tokens_per_sec"],
            "vs_sequential": round(run["tokens_per_sec"] / seq_tps, 3),
            "kv_cache_bytes": st["kv_cache_bytes"],
            "kv_blocks_total": st["kv_blocks_total"],
            "num_slots": cfg.num_slots,
            "deferred_admissions": st["deferred_admissions"],
        }

    slot_peak = max(results["slot"]["max_concurrent"], 1)
    best = max(results["paged"]["max_concurrent"],
               results["paged_int8"]["max_concurrent"])
    _emit({
        "metric": "serving_kv_memory_parity_max_concurrent",
        "value": best,
        "unit": "concurrent_requests",
        "vs_baseline": round(best / slot_peak, 3),
        "mode": "memory_parity",
        "kv_budget_bytes": budget,
        "block_size": block,
        "requests": n_req,
        "new_tokens": new_tokens,
        "prompt_tokens": prompt_len,
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "variants": results,
        "backend": jax.default_backend(),
    })


def _multimodal_bench() -> None:
    """`SERVE_BENCH_MODE=multimodal` (`make serve-bench-multimodal`):
    the micro-batch engines (docs/serving.md "Multimodal engines") vs
    the legacy one-call-per-request path, on the small-test towers —
    no checkpoint or tokenizer dependency. One BENCH row per engine
    type, each carrying `engine_type` (benchdiff treats rows at
    different engine types as incomparable, like offload placements).
    `value` = engine requests/s with all requests co-arriving,
    `vs_baseline` = speedup over sequential `pipeline(text)` calls —
    the micro-batching win: co-riders share ONE jitted forward (or
    denoise loop) instead of paying a batch-1 launch each."""
    from fengshen_tpu.serving.multimodal import create_multimodal_engine

    n_req = max(_env("REQUESTS", 8), 1)
    max_batch = max(_env("MAX_BATCH", 4), 1)
    prompts = [f"多模态 bench prompt {i}" for i in range(n_req)]

    jobs = (("batch_image", "image_generation"),
            ("embedding", "embedding"))
    for engine_name, task in jobs:
        import importlib
        mod = importlib.import_module(f"fengshen_tpu.pipelines.{task}")
        pipeline = mod.Pipeline(small_test=True,
                                seed=_env("SEED", 0))

        # compile both shapes outside the timed windows
        pipeline.run_batch([pipeline.warmup_input()] * max_batch)
        pipeline(pipeline.warmup_input())

        t0 = time.perf_counter()
        for p in prompts:
            pipeline(p)
        seq_rps = n_req / (time.perf_counter() - t0)

        engine = create_multimodal_engine(
            engine_name, pipeline,
            {"max_batch": max_batch, "gather_ms": 2.0,
             "max_queue": n_req})
        engine.start()
        t0 = time.perf_counter()
        reqs = [engine.submit(p) for p in prompts]
        for r in reqs:
            if not r.wait(timeout=300):
                raise RuntimeError(f"{engine_name} bench request "
                                   f"{r.request_id} never finished")
        eng_rps = n_req / (time.perf_counter() - t0)
        stats = engine.stats()
        engine.stop()

        _emit({
            "metric": f"serving_{engine_name}_requests_per_sec",
            "value": round(eng_rps, 2),
            "unit": "requests/s",
            "vs_baseline": round(eng_rps / seq_rps, 3),
            "mode": "multimodal",
            "engine_type": engine_name,
            "sequential_requests_per_sec": round(seq_rps, 2),
            "avg_batch": stats["avg_batch"],
            "batches_total": stats["batches_total"],
            "requests": n_req,
            "max_batch": max_batch,
            "backend": jax.default_backend(),
        })


def committed_per_forward(gamma: int, acceptance_rate: float) -> float:
    """Committed tokens per target forward per lane: every verify
    commits the accepted prefix plus one correction, so the mean is
    `1 + gamma * acceptance_rate` (an identity over the engine's
    spec_drafted/spec_accepted counters — the fast-lane smoke pins the
    math without a model forward). The non-spec tick is exactly 1.0."""
    if gamma < 0 or not 0.0 <= acceptance_rate <= 1.0:
        raise ValueError(f"bad spec stats: gamma={gamma} "
                         f"acceptance_rate={acceptance_rate}")
    return 1.0 + gamma * acceptance_rate


def _spec_prompts(model, params, vocab: int, prompt_len: int,
                  n_req: int, seed: int, probe: int,
                  probe_new: int = 32):
    """The repetitive-text workload: probe `probe` candidate tokens
    with ONE batched short generate and keep the `n_req` whose greedy
    continuations are most self-repetitive (fraction of positions
    matching one of the two previous tokens — what an ngram<=2 lookup
    can exploit). A random-init model has no real text to copy; this
    selects the rows where its greedy decode actually loops, the
    synthetic stand-in for extractive/repetitive serving traffic."""
    from fengshen_tpu.utils.generate import generate
    rng = np.random.RandomState(seed)
    cands = rng.randint(3, vocab - 1, probe).astype(np.int32)
    ids = jnp.asarray(np.repeat(cands[:, None], prompt_len, axis=1))
    out = np.asarray(generate(model, params,
                              max_new_tokens=probe_new,
                              input_ids=ids))[:, prompt_len:]
    rep = ((out[:, 2:] == out[:, 1:-1]) |
           (out[:, 2:] == out[:, :-2])).mean(1)
    best = cands[np.argsort(-rep, kind="stable")[:n_req]]
    return [np.full(prompt_len, int(t), np.int32) for t in best]


def _spec_bench(model, params, config, buckets, new_tokens) -> None:
    """Same engine, same prompts, spec off vs prompt_lookup: committed
    tokens per target forward (the >=1.8x bar), aggregate tokens/s
    (the >=1.3x bar), greedy token identity."""
    from fengshen_tpu.serving import EngineConfig

    slots = _env("SLOTS", 8)
    gamma = _env("SPEC_GAMMA", 4)
    ngram = _env("SPEC_NGRAM", 2)
    n_req = max(_env("REQUESTS", 8), 1)
    prompt_len = max(buckets[0] - 4, 1)
    max_len = int(model.config.max_position_embeddings)
    prompts = _spec_prompts(model, params, config.vocab_size,
                            prompt_len, n_req, _env("SEED", 0),
                            probe=_env("PROBE", 64),
                            probe_new=min(32, max_len - prompt_len))

    base_kw = dict(num_slots=slots, buckets=buckets,
                   max_new_tokens=new_tokens, max_queue=n_req,
                   eos_token_id=None, pad_token_id=0)
    off = _run_engine(model, params, prompts, EngineConfig(**base_kw))
    spec = _run_engine(
        model, params, prompts,
        EngineConfig(spec_mode="prompt_lookup", spec_gamma=gamma,
                     spec_ngram=ngram, **base_kw))
    st = spec["stats"]
    cpf = committed_per_forward(gamma, st["spec_acceptance_rate"])
    _emit({
        "metric": "serving_spec_committed_per_forward",
        "value": round(cpf, 3),
        "unit": "tokens/forward",
        # the non-spec tick commits exactly one token per lane per
        # weight stream, so cpf IS the vs-baseline ratio
        "vs_baseline": round(cpf, 3),
        "mode": "spec",
        "acceptance_rate": st["spec_acceptance_rate"],
        "spec_gamma": gamma,
        "spec_ngram": ngram,
        "tokens_per_sec": spec["tokens_per_sec"],
        "tokens_per_sec_off": off["tokens_per_sec"],
        "speedup_vs_off": round(spec["tokens_per_sec"] /
                                off["tokens_per_sec"], 3),
        "token_identical": spec["outputs"] == off["outputs"],
        "decode_ticks": st["decode_ticks"],
        "decode_ticks_off": off["stats"]["decode_ticks"],
        "requests": n_req,
        "num_slots": slots,
        "new_tokens": new_tokens,
        "prompt_tokens": prompt_len,
        "backend": jax.default_backend(),
    })


def main() -> None:
    mode = os.environ.get("SERVE_BENCH_MODE", "throughput")
    if mode == "multimodal":
        # no llama tower to build — the multimodal engines bench their
        # own small-test pipelines
        _multimodal_bench()
        return

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.serving import EngineConfig

    slots = _env("SLOTS", 8)
    n_req = _env("REQUESTS", 8)
    new_tokens = _env("NEW_TOKENS", 48)
    buckets = tuple(int(b) for b in os.environ.get(
        "SERVE_BENCH_BUCKETS", "32,64").split(","))
    # the spec verify scatters a gamma-wide tail past the cursor, so
    # the lane needs gamma extra positions (engine admission headroom)
    spec_headroom = _env("SPEC_GAMMA", 4) if mode == "spec" else 0
    # default shape sits in the weight-memory-bound decode regime (the
    # 300M-bench hidden/intermediate at 4 layers): batch-1 GEMV and
    # batch-8 GEMM stream the same weights, so the slot pool's batching
    # win is visible even on the CPU backend — tiny hidden sizes are
    # elementwise/dispatch-bound and hide it
    config = LlamaConfig(
        vocab_size=_env("VOCAB", 4096),
        hidden_size=_env("HIDDEN", 1024),
        intermediate_size=_env("INTER", 2816),
        num_hidden_layers=_env("LAYERS", 4),
        num_attention_heads=_env("HEADS", 8),
        max_position_embeddings=buckets[-1] + new_tokens + spec_headroom,
        dtype="float32")
    model = LlamaForCausalLM(config)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(_env("SEED", 0)))

    if mode == "memory_parity":
        _memory_parity(model, params, config, buckets, new_tokens)
        return
    if mode == "spec":
        _spec_bench(model, params, config, buckets, new_tokens)
        return

    rng = np.random.RandomState(_env("SEED", 0))
    span = max(buckets[-1] - 11, 1)  # varied lengths, any ladder size
    lengths = [min(buckets[-1], 12 + (i * 7) % span)
               for i in range(n_req)]
    prompts = [rng.randint(3, config.vocab_size - 1, n).astype(np.int32)
               for n in lengths]

    # sequential baseline (the legacy api/main.py path) vs the
    # continuous engine with all requests in flight together — the
    # same helpers the memory-parity mode times with
    seq_tps = _sequential_tps(model, params, prompts, new_tokens)
    run = _run_engine(
        model, params, prompts,
        EngineConfig(num_slots=slots, buckets=buckets,
                     max_new_tokens=new_tokens,
                     max_queue=max(n_req, 1),
                     eos_token_id=None, pad_token_id=0))
    eng_tps = run["tokens_per_sec"]
    stats = run["stats"]

    row = {
        "metric": "serving_engine_tokens_per_sec",
        "value": round(eng_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(eng_tps / seq_tps, 3),
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "ttft_avg_s": stats["ttft_avg_s"],
        "ttft_p95_s": stats["ttft_p95_s"],
        "slot_occupancy": stats["slot_occupancy"],
        "requests": n_req,
        "num_slots": slots,
        "new_tokens": new_tokens,
        "backend": jax.default_backend(),
    }
    # utilization column (docs/observability.md): forward-only FLOPs —
    # decode does no backward; present whenever the estimator supports
    # the benched model (it does: llama-shaped config)
    from fengshen_tpu.observability import (estimate_flops_per_token,
                                            peak_flops_per_chip)
    f_tok = estimate_flops_per_token(config, include_backward=False)
    if f_tok:
        peak = peak_flops_per_chip(jax.devices()[0].device_kind)
        row["mfu"] = float(f"{eng_tps * f_tok / (peak * len(jax.devices())):.4g}")
    _emit(row)


if __name__ == "__main__":
    main()
