"""Slot-pool KV cache: `num_slots` preallocated lanes, per-slot indices.

The models' flax cache (modeling_llama.py `_update_cache`) preallocates
`[B, max_len, kv, hd]` lanes but advances ONE scalar `cache_index` for
the whole batch — right for lockstep batch decode, wrong for a serving
pool where every lane is a different request at different progress.
These helpers build a pool whose `cache_index` leaves are `[num_slots]`
vectors (the attention layer's vector-index path picks that up and
writes each lane at its own position), scatter a freshly prefilled
request into a free lane, and reset reclaimed lanes — all shape-static,
so ONE jitted decode step serves every in-flight mix.

Leaf layout contract (holds for the whole zoo, scan_layers or not):
`cached_key`/`cached_value` end in (..., batch, max_len, kv_heads,
head_dim) and `cache_index` is scalar per layer — identified by path
via `utils.generate.is_cache_index_path`, the same predicate
`_rollback_cache` keys on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fengshen_tpu.utils.generate import is_cache_index_path


def init_slot_cache(model, num_slots: int):
    """Zeros cache pytree with `num_slots` lanes and VECTOR cache_index
    leaves (`[num_slots]`, or `[layers, num_slots]` under scan_layers).
    Abstract-init only — no param materialisation (same trick as
    `utils.generate._prefill_cache`)."""
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((num_slots, 1), jnp.int32),
                           init_cache=True))

    def build(path, leaf):
        if is_cache_index_path(path):
            # slotify: one write position per lane
            return jnp.zeros(leaf.shape + (num_slots,), jnp.int32)
        return jnp.zeros(leaf.shape, leaf.dtype)
    return jax.tree_util.tree_map_with_path(build, abstract["cache"])


def assign_slot(pool, primed, slot):
    """Scatter a single-request primed cache (batch 1, scalar index —
    the direct output of `_prefill_cache`) into lane `slot` of the pool.
    `slot` may be traced, so reclaiming a lane for the next queued
    request reuses the ONE compiled program. The full lane is
    overwritten, so stale K/V from the evicted request cannot leak."""
    def put(path, p, s):
        if is_cache_index_path(path):
            # p [..., S]; s scalar per layer
            return p.at[..., slot].set(s.astype(p.dtype))
        axis = p.ndim - 4  # (..., batch, max_len, kv, hd)
        start = [0] * p.ndim
        start[axis] = slot
        return jax.lax.dynamic_update_slice(p, s.astype(p.dtype),
                                            tuple(start))
    return jax.tree_util.tree_map_with_path(put, pool, primed)


def rollback_slots(cache, delta):
    """Per-slot analog of `utils.generate._rollback_cache`: lower each
    lane's cache_index by `delta` ([num_slots] vector). Sound for the
    same reason as the scalar version — entries past the index are
    masked out and overwritten in place. The engine's speculative tick
    leans on this every verify: the forward advances all lanes by
    gamma+1 and each lane rolls back its own rejected tail
    (serving/engine.py, docs/serving.md "Speculative decoding")."""
    def fix(path, leaf):
        if is_cache_index_path(path):
            return leaf - jnp.asarray(delta, leaf.dtype)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


def reset_free_slots(cache, active):
    """Clamp the cache_index of inactive lanes to 0 (`active` is a
    [num_slots] bool vector). Free lanes still ride through every decode
    step (static shapes); without the clamp their index would creep one
    per tick and eventually walk the garbage writes off the end of the
    preallocated lane.

    On a paged pool (serving/paged_cache.py) the same clamp also parks
    inactive lanes' `block_table` rows on the null block — their blocks
    may already be reallocated to another lane, so a stale row would
    let the lane's garbage write corrupt a live request's K/V."""
    def fix(path, leaf):
        if is_cache_index_path(path):
            return jnp.where(active, leaf, 0)
        if any(getattr(k, "key", None) == "block_table" for k in path):
            return jnp.where(active[:, None], leaf, 0)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)
