"""Continuous-batching serving engine (docs/serving.md).

Multiplexes many concurrent generation requests onto ONE jitted decode
step over a fixed slot pool — the serving-layer counterpart of
`utils.generate`'s TPU-native scan decode.
"""

from fengshen_tpu.serving.buckets import DEFAULT_BUCKETS, BucketLadder
from fengshen_tpu.serving.cache import (assign_slot, init_slot_cache,
                                        reset_free_slots, rollback_slots)
from fengshen_tpu.serving.engine import (CANCELLED, EXPIRED, FINISHED,
                                         QUEUED, REJECTED, RUNNING,
                                         ContinuousBatchingEngine,
                                         Draining, DuplicateRequest,
                                         EngineConfig, PromptTooLong,
                                         QueueFull, Request)
from fengshen_tpu.serving.metrics import EngineMetrics
from fengshen_tpu.serving.multimodal import (MULTIMODAL_ENGINE_TYPES,
                                             BatchImageEngine,
                                             EmbeddingEngine,
                                             MicroBatchEngine,
                                             create_multimodal_engine)
from fengshen_tpu.serving.paged_cache import (NULL_BLOCK, BlockAllocator,
                                              assign_paged,
                                              assign_slot_quantized,
                                              init_pool_cache)

__all__ = [
    "BatchImageEngine", "BlockAllocator", "BucketLadder",
    "DEFAULT_BUCKETS",
    "ContinuousBatchingEngine", "Draining", "DuplicateRequest",
    "EmbeddingEngine", "EngineConfig", "EngineMetrics",
    "MULTIMODAL_ENGINE_TYPES", "MicroBatchEngine",
    "NULL_BLOCK", "PromptTooLong", "QueueFull", "Request",
    "assign_paged", "assign_slot", "assign_slot_quantized",
    "create_multimodal_engine",
    "init_pool_cache", "init_slot_cache", "reset_free_slots",
    "rollback_slots", "QUEUED", "RUNNING", "FINISHED", "CANCELLED",
    "EXPIRED", "REJECTED",
]
