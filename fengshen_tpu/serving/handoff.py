"""KV-lane handoff: serialize a primed lane out of one engine and
resume it on another (prefill/decode disaggregation, docs/
disaggregation.md).

`export_lane` snapshots a RUNNING request's committed KV prefix,
history row and scheduler cursors into a versioned wire payload;
`adopt_lane` validates the header against the receiving engine and
scatters the lane into a free slot (or block run) so the next decode
tick resumes from the exact committed position. `detach_lane` retires
the source lane once the receiver has acknowledged adoption.

Wire-format invariants (version 1):

- KV travels int8-quantized with per-(token, head) fp32 absmax scales
  (`ops/int8_matmul.quantize_kv`) even when both tiers run fp32 — the
  4x payload shrink is the point of the int8 KV work (PR 6). An int8
  SOURCE pool exports its stored bits verbatim (no re-quantization),
  so an int8→int8 handoff is bit-identical end to end; an fp32 source
  pays exactly one quantization of the prefix (accuracy note in
  docs/disaggregation.md).
- The exported prefix covers physical positions ``[0, phys)`` only.
  The engine's decode tick writes ``_last_tok`` at ``phys`` BEFORE its
  forward, so the pending token rides in the payload header
  (``last_tok``) and the receiver's first tick re-commits it — the
  cache never carries a position the scheduler hasn't.
- Everything here is EAGER jnp gather/scatter on the scheduler lock —
  no new jitted programs, so the engine's pinned compile counts
  (one decode program, one assign program, one prefill per bucket)
  are untouched by handoffs.

Layout/dtype are free to differ between the tiers: the receiver
re-bases the lane on its own pool (slot or paged, fp32 or int8); only
the model fingerprint and the generation controls must match exactly.
"""

from __future__ import annotations

import base64
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.disagg import transfer
from fengshen_tpu.ops.int8_matmul import dequantize_kv, quantize_kv
from fengshen_tpu.serving.engine import RUNNING, Request
from fengshen_tpu.serving.paged_cache import (_map_attn_dicts,
                                              blocks_for_tokens)

#: wire header constants — adopt declines any mismatch with "version"
WIRE_KIND = "fstpu-kv-handoff"
WIRE_VERSION = 1

#: terminal state of a lane that left this engine via `detach_lane`
HANDED_OFF = "handed_off"

#: terminal state of a lane that left via live evacuation during drain
#: (docs/fault_tolerance.md "Preemption runbook") — same mechanics as
#: handed_off, but the API layer answers the blocked POST with a
#: disagg-style redirect so the router re-collects from the adopter
EVACUATED = "evacuated"

#: EngineConfig fields that must match exactly across a handoff: the
#: receiver resumes mid-generation, so any divergence here would
#: silently change the sampled distribution or the stop condition
CONTROL_FIELDS = ("eos_token_id", "pad_token_id", "do_sample",
                  "temperature", "top_k", "top_p", "repetition_penalty",
                  "no_repeat_ngram_size", "min_length", "seed")


class HandoffError(Exception):
    """Export-side failure (request not exportable from this engine)."""


class AdoptDecline(Exception):
    """Adopt-side refusal; `reason` is the wire/metric label."""

    def __init__(self, reason: str, message: Optional[str] = None):
        super().__init__(message or reason)
        self.reason = reason


def _b64(arr: np.ndarray) -> dict:
    return {"dtype": str(arr.dtype), "shape": list(arr.shape),
            "data": base64.b64encode(
                np.ascontiguousarray(arr).tobytes()).decode("ascii")}


def _unb64(d: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["data"]),
        dtype=np.dtype(d["dtype"])).reshape(d["shape"])


def _gather_lane(leaf, axis: int, phys: int, slot: Optional[int],
                 blocks: Optional[List[int]]):
    """The committed prefix ``[0, phys)`` of one lane as a host array.

    `axis` is the pool's slot axis (vals: ndim-4, scales: ndim-3);
    leading layer axes pass through untouched. Paged pools gather the
    lane's blocks and merge the (block, offset) axes back into one
    contiguous token axis — the inverse of `assign_paged`'s scatter.
    """
    if blocks is None:
        lane = jnp.take(leaf, slot, axis=axis)
    else:
        g = jnp.take(leaf, jnp.asarray(blocks, jnp.int32), axis=axis)
        shp = g.shape
        lane = g.reshape(shp[:axis] + (shp[axis] * shp[axis + 1],) +
                         shp[axis + 2:])
    return np.asarray(jax.lax.slice_in_dim(lane, 0, phys, axis=axis))


def _scatter_lane(leaf, axis: int, val, slot: Optional[int],
                  positions: Optional[np.ndarray]):
    """Write a `[..., phys, ...]` lane prefix into the pool at `slot`
    (slot layout) or at flat token `positions` (paged layout)."""
    val = jnp.asarray(val)
    if positions is None:
        idx = (slice(None),) * axis + (slot,
                                       slice(0, val.shape[axis]))
        return leaf.at[idx].set(val)
    nb, bs = leaf.shape[axis], leaf.shape[axis + 1]
    flat = leaf.reshape(leaf.shape[:axis] + (nb * bs,) +
                        leaf.shape[axis + 2:])
    idx = (slice(None),) * axis + (positions,)
    return flat.at[idx].set(val).reshape(leaf.shape)


def export_lane(engine, request_id: str) -> dict:
    """Serialize the RUNNING request `request_id` into a sealed wire
    payload. The engine keeps decoding the lane afterwards — export is
    a SNAPSHOT; call `detach_lane` only once the receiver has adopted.

    Raises `HandoffError` when the request isn't currently running in
    a lane (still queued, already finished, unknown) or the engine is
    speculative (a mid-verify draft window has no committed cursor to
    cut at).
    """
    with engine._cv:
        if engine.spec:
            raise HandoffError(
                "speculative engines do not export lanes "
                "(no committed cursor inside a verify window)")
        req = None
        for r in engine._slot_req:
            if r is not None and r.request_id == request_id:
                req = r
                break
        if req is None or req.state != RUNNING:
            raise HandoffError(
                f"request {request_id!r} is not running in a lane")
        slot = req.slot
        phys = int(engine._phys[slot])
        pos = int(engine._pos[slot])
        last_tok = int(engine._last_tok[slot])
        bucket = phys - (len(req.tokens) - 1)
        blocks = engine._slot_blocks[slot] if engine.paged else None
        int8_src = engine.config.kv_dtype == "int8"
        layers: List[dict] = []

        def grab(d):
            entry = {}
            for name, leaf_key, scale_key in (
                    ("k", "cached_key", "cached_key_scale"),
                    ("v", "cached_value", "cached_value_scale")):
                if int8_src:
                    q = _gather_lane(d[leaf_key], d[leaf_key].ndim - 4,
                                     phys, slot, blocks)
                    s = _gather_lane(d[scale_key],
                                     d[scale_key].ndim - 3, phys, slot,
                                     blocks)
                else:
                    lane = _gather_lane(d[leaf_key],
                                        d[leaf_key].ndim - 4, phys,
                                        slot, blocks)
                    qj, sj = quantize_kv(jnp.asarray(lane))
                    q, s = np.asarray(qj), np.asarray(
                        sj, dtype=np.float32)
                entry[name] = _b64(np.asarray(q))
                entry[name + "_scale"] = _b64(
                    np.asarray(s, dtype=np.float32))
            layers.append(entry)
            return d

        _map_attn_dicts(engine._cache, grab)
        now = engine._clock()
        deadline_remaining = None if req.deadline is None else \
            max(float(req.deadline - now), 0.0)
        payload = {
            "kind": WIRE_KIND,
            "version": WIRE_VERSION,
            "model_fingerprint": repr(engine.model.config),
            "request_id": req.request_id,
            "source": {"kv_layout": engine.config.kv_layout,
                       "kv_dtype": engine.config.kv_dtype},
            "wire_dtype": "int8",
            "bucket": int(bucket),
            "phys": phys,
            "pos": pos,
            "last_tok": last_tok,
            "prompt": [int(t) for t in req.prompt],
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": int(req.max_new_tokens),
            "deadline_remaining_s": deadline_remaining,
            "ttft_s": req.ttft_s,
            "controls": {f: getattr(engine.config, f)
                         for f in CONTROL_FIELDS},
            "trace": {"trace_id": req.timeline.trace_id,
                      "parent_span_id": req.timeline.parent_span_id},
            "layers": layers,
        }
        req.timeline.add(now, "handoff_export", phys=phys,
                         layers=len(layers))
    return transfer.seal(payload)


def _validate_header(engine, payload: dict) -> None:
    if payload.get("kind") != WIRE_KIND or \
            payload.get("version") != WIRE_VERSION:
        raise AdoptDecline("version",
                           f"unsupported wire header "
                           f"{payload.get('kind')!r} "
                           f"v{payload.get('version')!r}")
    if not transfer.verify_checksum(payload):
        raise AdoptDecline("checksum", "payload checksum mismatch")
    if payload.get("model_fingerprint") != repr(engine.model.config):
        raise AdoptDecline("model_fingerprint",
                           "model config differs between tiers")
    controls = payload.get("controls") or {}
    for f in CONTROL_FIELDS:
        if f not in controls or controls[f] != getattr(engine.config, f):
            raise AdoptDecline(
                "controls", f"generation control {f!r} differs "
                f"({controls.get(f)!r} != "
                f"{getattr(engine.config, f)!r})")


def adopt_lane(engine, payload: dict) -> Request:
    """Resume an exported lane on this engine. Returns the registered
    RUNNING `Request` (its `wait()` unblocks when decode finishes
    here). Raises `AdoptDecline` — and leaves the engine untouched —
    on every refusal path; the decline reason travels back in the
    adopt-ack so the source can count its fallback precisely.
    """
    _validate_header(engine, payload)
    prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
    tokens = [int(t) for t in payload["tokens"]]
    bucket = int(payload["bucket"])
    phys = int(payload["phys"])
    pos = int(payload["pos"])
    max_new = int(payload["max_new_tokens"])
    remaining = max_new - len(tokens)
    if (len(tokens) < 1 or remaining < 1 or len(prompt) < 1 or
            bucket < len(prompt) or
            phys != bucket + len(tokens) - 1 or
            pos != len(prompt) + len(tokens) - 1):
        raise AdoptDecline("payload_invalid",
                           "inconsistent lane cursors in payload")
    with engine._cv:
        if engine.spec:
            raise AdoptDecline("spec_engine",
                              "speculative engines do not adopt lanes")
        if engine._draining:
            raise AdoptDecline("draining", "engine is draining")
        if bucket + max_new > engine.seq_capacity:
            raise AdoptDecline(
                "capacity", f"lane needs {bucket + max_new} positions; "
                f"this engine's KV capacity is {engine.seq_capacity}")
        for live in list(engine._queue) + [
                r for r in engine._slot_req if r is not None]:
            if live.request_id == payload["request_id"]:
                raise AdoptDecline("duplicate_request_id",
                                   f"{payload['request_id']!r} is "
                                   f"already {live.state} here")
        slot = None
        for i in range(engine.config.num_slots):
            if not engine._active[i]:
                slot = i
                break
        if slot is None:
            raise AdoptDecline("no_free_slot", "all lanes busy")
        blocks = None
        positions = None
        table_row = None
        if engine.paged:
            need = blocks_for_tokens(bucket + max_new,
                                     engine.block_size)
            blocks = engine._allocator.alloc(need)
            if blocks is None:
                raise AdoptDecline("kv_blocks_exhausted",
                                   f"need {need} free KV blocks")
        try:
            if engine.paged:
                table_row = np.zeros((engine.max_blocks_per_slot,),
                                     np.int32)
                table_row[:len(blocks)] = blocks
                positions = np.concatenate(
                    [np.arange(engine.block_size) + b * engine.block_size
                     for b in blocks]).astype(np.int32)[:phys]
            new_cache = _scatter_payload(engine, payload, slot, phys,
                                         positions, table_row)
        except BaseException:  # noqa: BLE001 — release + re-raise
            # any failure before the commit — a decline or an
            # unexpected error — must return the blocks to the pool
            if blocks is not None:
                engine._allocator.free(blocks)
            raise
        # lane accepted: commit pool + rows + scheduler state together
        engine._cache = new_cache
        L = engine.seq_capacity
        row, mask_row = engine.ladder.pad_prompt(
            prompt, bucket, engine.config.pad_token_id)
        hist_row = np.zeros((L,), np.int32)
        hist_row[:bucket] = row
        hist_row[bucket:phys] = np.asarray(tokens[:-1], np.int32)
        full_mask = np.ones((L,), np.int32)
        full_mask[:bucket] = mask_row
        engine._history = engine._history.at[slot].set(
            jnp.asarray(hist_row))
        engine._mask = engine._mask.at[slot].set(jnp.asarray(full_mask))
        if engine.paged:
            engine._slot_blocks[slot] = blocks
        now = engine._clock()
        deadline = payload.get("deadline_remaining_s")
        req = Request(prompt, max_new, str(payload["request_id"]),
                      None if deadline is None else now + float(deadline),
                      now, epoch=engine._wall())
        req.tokens = tokens
        req.ttft_s = payload.get("ttft_s")
        trace = payload.get("trace") or {}
        req.timeline.trace_id = trace.get("trace_id")
        req.timeline.parent_span_id = trace.get("parent_span_id")
        req.timeline.add(now, "adopted", slot=slot, bucket=bucket,
                         generated=len(tokens),
                         source_layout=payload["source"]["kv_layout"],
                         source_dtype=payload["source"]["kv_dtype"])
        req.state = RUNNING
        req.slot = slot
        engine._slot_req[slot] = req
        engine._active[slot] = True
        engine._last_tok[slot] = int(payload["last_tok"])
        engine._pos[slot] = pos
        engine._phys[slot] = phys
        # the adopter journals the lane too: after a hard kill of the
        # source, this replica's `GET /partial/<id>` carries the
        # committed prefix the router resumes from
        engine._journal_add_locked(req)
        engine.metrics.count("admitted")
        engine._log({"event": "serving_adopt",
                     "request_id": req.request_id, "slot": slot,
                     "phys": phys, "generated": len(tokens),
                     "source": payload["source"]})
        engine._cv.notify_all()
    return req


def _scatter_payload(engine, payload: dict, slot: int, phys: int,
                     positions: Optional[np.ndarray],
                     table_row: Optional[np.ndarray]):
    """Rebuild the engine's KV pool with the wire lane written into
    `slot`. int8 receivers take the wire bits verbatim (an int8→int8
    handoff never round-trips through float); fp32 receivers store the
    dequantized prefix. Raises AdoptDecline("shape") before touching
    anything when any layer disagrees with the local pool geometry."""
    int8_dst = engine.config.kv_dtype == "int8"
    layers = payload["layers"]
    n_layers = [0]

    def check(d):
        i = n_layers[0]
        n_layers[0] += 1
        if i >= len(layers):
            raise AdoptDecline("shape", "payload has too few layers")
        for name, leaf_key in (("k", "cached_key"),
                               ("v", "cached_value")):
            leaf = d[leaf_key]
            axis = leaf.ndim - 4
            want = (leaf.shape[:axis] + (phys,) + leaf.shape[axis + 2:])
            got = tuple(layers[i][name]["shape"])
            if got != want:
                raise AdoptDecline(
                    "shape", f"layer {i} {name} lane shape {got} does "
                    f"not fit local pool geometry {want}")
        return d

    _map_attn_dicts(engine._cache, check)
    if n_layers[0] != len(layers):
        raise AdoptDecline("shape", "payload has too many layers")
    it = iter(layers)

    def put(d):
        entry = next(it)
        out = dict(d)
        for name, leaf_key, scale_key in (
                ("k", "cached_key", "cached_key_scale"),
                ("v", "cached_value", "cached_value_scale")):
            q = _unb64(entry[name])
            s = _unb64(entry[name + "_scale"])
            leaf = d[leaf_key]
            axis = leaf.ndim - 4
            if int8_dst:
                out[leaf_key] = _scatter_lane(leaf, axis, q, slot,
                                              positions)
                sleaf = d[scale_key]
                out[scale_key] = _scatter_lane(sleaf, sleaf.ndim - 3,
                                               s, slot, positions)
            else:
                val = dequantize_kv(jnp.asarray(q), jnp.asarray(s),
                                    leaf.dtype)
                out[leaf_key] = _scatter_lane(leaf, axis, val, slot,
                                              positions)
        out["cache_index"] = d["cache_index"].at[..., slot].set(
            jnp.int32(phys))
        if table_row is not None:
            out["block_table"] = d["block_table"].at[..., slot, :].set(
                jnp.asarray(table_row))
        return out

    return _map_attn_dicts(engine._cache, put)


def detach_lane(engine, request_id: str,
                target: Optional[str] = None,
                evacuated: bool = False) -> bool:
    """Retire a lane whose payload a decode peer has ADOPTED: free the
    slot/blocks, mark the request `handed_off` (its `wait()` unblocks;
    the coordinator returns the redirect instead of local tokens) and
    park its timeline in the debug ring. Returns False — and changes
    nothing — when the request already finished locally (the race
    where decode outran the push; the source result stands and the
    adopted twin gets cancelled).

    `evacuated=True` is the live-evacuation flavor (drain-time lane
    rescue, docs/fault_tolerance.md "Preemption runbook"): the state is
    `evacuated`, the timeline gets the terminal `evacuated` event (with
    the adopter + committed-token count), and `req.evac_target` lets
    the API layer answer the blocked POST with a redirect the router
    re-collects transparently."""
    state = EVACUATED if evacuated else HANDED_OFF
    with engine._cv:
        req = None
        for r in engine._slot_req:
            if r is not None and r.request_id == request_id:
                req = r
                break
        if req is None or req.state != RUNNING:
            return False
        slot = req.slot
        engine._slot_req[slot] = None
        engine._active[slot] = False
        engine._phys[slot] = 0
        engine._pos[slot] = 0
        if engine.paged and engine._slot_blocks[slot]:
            engine._allocator.free(engine._slot_blocks[slot])
            engine._slot_blocks[slot] = []
        req.state = state
        req.finish_reason = state
        req.slot = None
        if evacuated:
            req.evac_target = target
        end_t = engine._clock()
        req.timeline.add(end_t, state,
                         **dict(({"target": target} if target else {}),
                                **({"tokens": len(req.tokens)}
                                   if evacuated else {})))
        engine._recent.append(engine._request_dict(
            req, phases=req.timeline.phases(end_t)))
        engine._log({"event": "serving_evacuate" if evacuated
                     else "serving_handoff",
                     "request_id": req.request_id,
                     "tokens": len(req.tokens), "target": target})
        # terminal stream sync AFTER evac_target is stamped: a live
        # SSE reader gets any tail tokens plus the `evacuated` event
        # pointing at the adopter (docs/streaming.md "Reconnect")
        engine._sync_stream(req)
        req._done.set()
        return True
