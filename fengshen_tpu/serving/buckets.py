"""Prefill bucket ladder: bounded compile shapes for variable prompts.

Continuous batching admits prompts of arbitrary length, but every
distinct prefill width is one XLA compilation. Padding each prompt LEFT
to the smallest bucket of a short geometric ladder (default
64/128/256/512) bounds the compile set to `len(buckets)` programs while
wasting at most ~2x prefill FLOPs in the worst case — the same trade
the repo's SFT packing and `utils.generate`'s left-padded batching
already make (reference idiom: llama_generate.py:17-40 left padding).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

#: geometric ladder; tune per deployment (docs/serving.md)
DEFAULT_BUCKETS = (64, 128, 256, 512)


class BucketLadder:
    """Smallest-bucket-that-fits selection plus left-padding."""

    def __init__(self, buckets: Sequence[int] = DEFAULT_BUCKETS):
        buckets = tuple(int(b) for b in buckets)
        if not buckets:
            raise ValueError("BucketLadder needs at least one bucket")
        if any(b <= 0 for b in buckets) or \
                any(a >= b for a, b in zip(buckets, buckets[1:])):
            raise ValueError(
                f"buckets must be positive and strictly ascending: "
                f"{buckets}")
        self.buckets = buckets

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, length: int) -> Optional[int]:
        """Smallest bucket >= length; None when the prompt outgrows the
        ladder (the engine rejects instead of silently truncating)."""
        if length <= 0:
            raise ValueError(f"prompt length must be positive: {length}")
        for b in self.buckets:
            if length <= b:
                return b
        return None

    def pad_prompt(self, ids, bucket: int, pad_token_id: int = 0):
        """LEFT-pad `ids` (1-D int sequence) to `bucket`; returns
        (ids [bucket], mask [bucket]) int32 numpy rows. Left padding
        keeps the last real token in the last column, so the prefill's
        final-position logits are the next-token logits — exactly
        `utils.generate.generate`'s convention."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        if len(ids) > bucket:
            raise ValueError(f"prompt of {len(ids)} tokens does not fit "
                             f"bucket {bucket}")
        out = np.full((bucket,), pad_token_id, np.int32)
        mask = np.zeros((bucket,), np.int32)
        out[bucket - len(ids):] = ids
        mask[bucket - len(ids):] = 1
        return out, mask
