"""Multimodal serving engines: micro-batched batch-image and embedding
(docs/serving.md "Multimodal engines").

The continuous-batching engine (engine.py) is token-autoregressive —
its slot pool, bucket ladder and per-tick decode make no sense for a
diffusion UNet or a CLIP text tower, whose unit of work is one whole
forward (or a fixed denoise loop) per request. What those workloads DO
want is micro-batching: requests that arrive within a short gather
window ride one jitted batch instead of compiling/launching per
request.

`MicroBatchEngine` supplies the shared machinery — bounded queue,
gather window, worker thread, warmup, drain, `/stats` — and delegates
the actual model work to the pipeline's `run_batch(inputs) ->
list[result]` hook (mirroring how the continuous engine delegates
`encode`/`decode`). Two concrete engine types ride it:

- `BatchImageEngine`  (`engine_type="batch_image"`) — text-to-image
  diffusion (pipelines/image_generation.py).
- `EmbeddingEngine`   (`engine_type="embedding"`) — text embeddings
  (pipelines/embedding.py).

The API layer (api/main.py) dispatches on `engine_type` and maps the
same backpressure exceptions the continuous engine raises (QueueFull →
429, Draining → 503, DuplicateRequest → 409), so the fleet router's
retry contract holds across engine types.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Optional

from fengshen_tpu.serving.engine import (Draining, DuplicateRequest,
                                         QueueFull)

#: request states (string-valued on purpose — this engine has no
#: slot/evacuation machinery, so the continuous engine's richer state
#: constants would be a false equivalence)
MM_QUEUED = "queued"
MM_FINISHED = "finished"
MM_FAILED = "failed"
MM_CANCELLED = "cancelled"


class MMRequest:
    """One submitted multimodal request; `wait()` blocks the HTTP
    handler thread until the worker fulfils it."""

    def __init__(self, request_id: str, payload: Any):
        self.request_id = request_id
        self.payload = payload
        self.result: Any = None
        self.error: Optional[str] = None
        self.state = MM_QUEUED
        self._done = threading.Event()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def _finish(self, result: Any) -> None:
        self.result = result
        self.state = MM_FINISHED
        self._done.set()

    def _fail(self, error: str) -> None:
        self.error = error
        self.state = MM_FAILED
        self._done.set()

    def _cancel(self, reason: str) -> None:
        self.error = reason
        self.state = MM_CANCELLED
        self._done.set()


class MicroBatchEngine:
    """Gather-window micro-batching over `pipeline.run_batch`.

    `max_batch` bounds one jitted launch; `gather_ms` is how long the
    worker waits for co-riders after the first request of a batch
    lands (0 = take whatever is queued, never sleep for more).
    `clock` is injectable for deterministic tests.
    """

    engine_type = "micro_batch"

    def __init__(self, pipeline: Any, max_batch: int = 4,
                 gather_ms: float = 2.0, max_queue: int = 64,
                 log=None, clock=time.monotonic):
        if not hasattr(pipeline, "run_batch"):
            raise ValueError(
                f"engine {self.engine_type!r} needs a pipeline exposing "
                "run_batch(inputs) -> list[result] (tasks "
                "'image_generation' / 'embedding'), not a per-call "
                "text pipeline")
        self.pipeline = pipeline
        self.max_batch = int(max_batch)
        self.gather_ms = float(gather_ms)
        self.max_queue = int(max_queue)
        self._log = log or (lambda *a, **k: None)
        self._clock = clock
        self._t0 = clock()
        self._cv = threading.Condition()
        self._queue: list[MMRequest] = []
        #: request_id → live request (the fleet router's idempotent
        #: retry dedupe, same 409 contract as the continuous engine)
        self._live: "OrderedDict[str, MMRequest]" = OrderedDict()
        self._in_flight = 0
        self._draining = False
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._counter = 0
        self._requests_total = 0
        self._batches_total = 0
        self._batched_requests = 0
        self._warmup_s: Optional[float] = None

    # ---- lifecycle --------------------------------------------------

    def warmup(self) -> float:
        """Compile the batch program(s) before serving: one throwaway
        run_batch per batch width would be wasteful — a single width-1
        call compiles the model; jax re-pads/rebuilds per width lazily
        only if callers vary widths (the engine always pads to
        max_batch for exactly this reason)."""
        t0 = time.perf_counter()
        self.pipeline.run_batch([self.pipeline.warmup_input()]
                                * self.max_batch)
        self._warmup_s = time.perf_counter() - t0
        return self._warmup_s

    def start(self) -> None:
        with self._cv:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._serve_loop, daemon=True,
            name=f"fstpu-{self.engine_type}")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._cv:
            self._running = False
            for req in self._queue:
                req._cancel("engine stopped")
            self._queue.clear()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # ---- admission --------------------------------------------------

    def submit(self, payload: Any,
               request_id: Optional[str] = None) -> MMRequest:
        if payload is None or (isinstance(payload, str)
                               and not payload.strip()):
            raise ValueError("empty input")
        with self._cv:
            if self._draining:
                raise Draining("replica draining")
            if request_id is not None and request_id in self._live:
                raise DuplicateRequest(
                    f"request_id {request_id!r} already in flight")
            if len(self._queue) >= self.max_queue:
                raise QueueFull(
                    f"queue full ({self.max_queue} requests)")
            if request_id is None:
                self._counter += 1
                request_id = f"{self.engine_type}-{self._counter}"
            req = MMRequest(str(request_id), payload)
            self._queue.append(req)
            self._live[req.request_id] = req
            self._requests_total += 1
            self._cv.notify_all()
            return req

    def cancel(self, request_id: str) -> bool:
        with self._cv:
            req = self._live.get(request_id)
            if req is None or req.state != MM_QUEUED:
                return False
            if req in self._queue:
                self._queue.remove(req)
                req._cancel("cancelled")
                self._live.pop(request_id, None)
                return True
            return False    # already picked up by the worker

    # ---- drain / idle (docs/fleet.md contract) ----------------------

    def begin_drain(self) -> None:
        with self._cv:
            self._draining = True
            self._cv.notify_all()

    def idle(self) -> bool:
        with self._cv:
            return not self._queue and self._in_flight == 0

    # ---- worker -----------------------------------------------------

    def _take_batch(self) -> list[MMRequest]:
        """Under _cv: wait for work, then gather up to max_batch. The
        gather window only ever delays the FIRST rider of a batch —
        once the window closes the batch launches with whoever came."""
        while self._running and not self._queue:
            self._cv.wait(0.05)
        if not self._running:
            return []
        if self.gather_ms > 0 and len(self._queue) < self.max_batch:
            deadline = self._clock() + self.gather_ms / 1000.0
            while (self._running
                   and len(self._queue) < self.max_batch
                   and self._clock() < deadline):
                self._cv.wait(self.gather_ms / 1000.0)
        batch = self._queue[:self.max_batch]
        del self._queue[:len(batch)]
        self._in_flight += len(batch)
        return batch

    def _serve_loop(self) -> None:
        while True:
            with self._cv:
                if not self._running:
                    return
                batch = self._take_batch()
            if not batch:
                continue
            try:
                results = self.pipeline.run_batch(
                    [r.payload for r in batch])
                if len(results) != len(batch):
                    raise ValueError(
                        f"run_batch returned {len(results)} results "
                        f"for {len(batch)} inputs")
                for req, res in zip(batch, results):
                    req._finish(res)
            except Exception as e:  # noqa: BLE001 — a bad batch must
                # answer its requests, not kill the worker thread
                self._log(f"[{self.engine_type}] batch failed: {e}")
                for req in batch:
                    req._fail(str(e)[:500])
            finally:
                with self._cv:
                    self._in_flight -= len(batch)
                    self._batches_total += 1
                    self._batched_requests += len(batch)
                    for req in batch:
                        self._live.pop(req.request_id, None)

    # ---- observability ----------------------------------------------

    def stats(self) -> dict:
        with self._cv:
            avg = (self._batched_requests / self._batches_total
                   if self._batches_total else 0.0)
            return {
                "engine": self.engine_type,
                "engine_type": self.engine_type,
                "requests_total": self._requests_total,
                "batches_total": self._batches_total,
                "avg_batch": round(avg, 3),
                "max_batch": self.max_batch,
                "queue_depth": len(self._queue),
                "in_flight": self._in_flight,
                "uptime_s": self._clock() - self._t0,
                "warmup_s": self._warmup_s,
                "draining": self._draining,
            }


class BatchImageEngine(MicroBatchEngine):
    """Text-to-image micro-batching (Taiyi Stable Diffusion): each
    batch is one jitted denoise loop + VAE decode over all riders'
    prompts (pipelines/image_generation.py)."""

    engine_type = "batch_image"


class EmbeddingEngine(MicroBatchEngine):
    """Text-embedding micro-batching (Taiyi CLIP text tower): each
    batch is one jitted `get_text_features` over all riders' prompts
    (pipelines/embedding.py)."""

    engine_type = "embedding"


#: api/main.py's engine-name → class table; ServerConfig validates
#: against exactly these names plus "simple"/"continuous"
MULTIMODAL_ENGINE_TYPES: dict = {
    "batch_image": BatchImageEngine,
    "embedding": EmbeddingEngine,
}


def create_multimodal_engine(engine_name: str, pipeline: Any,
                             engine_args: Optional[dict] = None,
                             log=None) -> MicroBatchEngine:
    """Build (but do not warm or start) the named multimodal engine —
    the multimodal sibling of api.main.create_continuous_engine.
    `engine_args` is the config ENGINE block (max_batch, gather_ms,
    max_queue)."""
    cls = MULTIMODAL_ENGINE_TYPES.get(engine_name)
    if cls is None:
        raise ValueError(
            f"unknown multimodal engine {engine_name!r}; expected one "
            f"of {sorted(MULTIMODAL_ENGINE_TYPES)}")
    return cls(pipeline, log=log, **(engine_args or {}))
