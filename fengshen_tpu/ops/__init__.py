"""Compute ops: XLA-first kernels with Pallas for the hot paths.

TPU-native replacement for the reference's native-kernel tier
(reference: fengshen/models/megatron/fused_kernels/ CUDA softmax/layernorm,
fengshen/models/megatron/layers/flash_attention.py, and the DeepSpeed sparse
attention configs in layers/utils.py:187-289). XLA already fuses
scale+mask+softmax and layernorm chains; Pallas kernels cover flash/splash
attention and block-sparse layouts.
"""

from fengshen_tpu.ops.norms import RMSNorm, LayerNorm, ScaleNorm, get_norm
from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.rotary import rotary_cos_sin, apply_rotary_pos_emb
from fengshen_tpu.ops.alibi import alibi_slopes, alibi_bias
from fengshen_tpu.ops.masks import (
    causal_mask,
    sliding_window_mask,
    bigbird_mask,
    bigbird_block_layout,
    longformer_mask,
    longformer_block_layout,
    fixed_sparsity_mask,
    fixed_block_layout,
    make_attention_bias,
)
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.ulysses_attention import (
    ulysses_attention_sharded, sequence_parallel_attention)
from fengshen_tpu.ops.init_functions import get_init_methods
from fengshen_tpu.ops.moe import (SwitchMoE,
                                  load_balancing_loss,
                                  MOE_PARTITION_RULES)
from fengshen_tpu.ops.gmlp import GMLPBlock, SpatialGatingUnit, TinyAttention
from fengshen_tpu.ops.soft_embedding import SoftEmbedding

__all__ = [
    "RMSNorm", "LayerNorm", "ScaleNorm", "get_norm",
    "get_activation",
    "rotary_cos_sin", "apply_rotary_pos_emb",
    "alibi_slopes", "alibi_bias",
    "causal_mask", "sliding_window_mask", "bigbird_mask", "longformer_mask",
    "fixed_sparsity_mask",
    "bigbird_block_layout", "longformer_block_layout", "fixed_block_layout",
    "make_attention_bias",
    "dot_product_attention",
    "ulysses_attention_sharded", "sequence_parallel_attention",
    "get_init_methods",
    "SwitchMoE", "load_balancing_loss", "MOE_PARTITION_RULES",
    "GMLPBlock", "SpatialGatingUnit", "TinyAttention",
    "SoftEmbedding",
]
