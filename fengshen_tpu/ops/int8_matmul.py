"""Dynamic int8×int8 matmul for the LM-head (training-time lever).

docs/performance.md names the 32k-vocab LM-head matmul as the largest
non-attention residue at 79% MFU. On v5e the MXU runs int8×int8→int32 at
2× the bf16 rate, so quantizing BOTH operands dynamically (per-row absmax
for activations, per-column absmax for the weight) halves the head's
matmul time at the cost of ≤1e-2 relative logit error.

Backward is straight-through: gradients are computed against the bf16
inputs (the quantization is treated as identity), so the optimizer sees
exact-matmul gradients up to the forward's quantization noise in the
loss. No reference counterpart — the reference's int8 is serving-only
(bitsandbytes); this is a TPU-native training lever.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _quant_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., K] → int8 with one absmax scale per row."""
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax.astype(jnp.float32), 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127,
                 127).astype(jnp.int8)
    return q, scale


def _quant_cols(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[K, N] → int8 with one absmax scale per output column."""
    absmax = jnp.max(jnp.abs(w), axis=0, keepdims=True)
    scale = jnp.maximum(absmax.astype(jnp.float32), 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127,
                 127).astype(jnp.int8)
    return q, scale


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., head_dim] K/V → (int8 values, fp32 per-head absmax scale
    [...]). The KV-cache flavor of `_quant_rows`: one scale per
    (token, head) vector, so the serving pool stores 1 byte/element
    plus a float per head — the int8 KV mode of
    `fengshen_tpu/serving/paged_cache.py` and the attention read in
    `modeling_llama._update_cache`."""
    absmax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.maximum(absmax.astype(jnp.float32), 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of `quantize_kv`; XLA fuses this into the attention read
    so the fp tensor never materializes in HBM."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


@jax.custom_vjp
def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """x [..., K] @ w [K, N] via dynamic int8 quantization of both
    operands; returns x.dtype."""
    xq, sx = _quant_rows(x)
    wq, sw = _quant_cols(w)
    acc = lax.dot_general(xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * sw).astype(x.dtype)


def _fwd(x, w):
    return int8_matmul(x, w), (x, w)


def _bwd(res, g):
    x, w = res
    # straight-through: exact-matmul gradients in the inputs' dtype
    dx = lax.dot_general(g, w, (((g.ndim - 1,), (1,)), ((), ())))
    x2d = x.reshape(-1, x.shape[-1])
    g2d = g.reshape(-1, g.shape[-1])
    dw = lax.dot_general(x2d, g2d, (((0,), (0,)), ((), ())))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_matmul.defvjp(_fwd, _bwd)
