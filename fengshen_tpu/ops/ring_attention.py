"""Ring attention — sequence/context parallelism over the ICI mesh.

The reference has **no** sequence parallelism (SURVEY.md §5.7: max context is
per-device, flash/sparse kernels only scale the constant factor). This module
fills that gap the TPU-native way: the sequence dim is sharded over the
'sequence' mesh axis, and k/v shards rotate around the ring with
`jax.lax.ppermute` while each device accumulates its queries' attention with
an online softmax — compute overlaps the ICI transfer and per-device memory
stays O(S/ring) (Liu et al., Ring Attention with Blockwise Transformers).

`ring_attention` is the shard_map-body (axis_name in scope);
`ring_attention_sharded` wraps it for callers holding globally-sharded
arrays.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from fengshen_tpu.compat import axis_size as _axis_size, shard_map

from fengshen_tpu.parallel.mesh import BATCH_AXES, SEQUENCE_AXIS, get_mesh

_NEG_INF = -1e30


def _block_scores(q, k, scale):
    return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   segment_ids: Optional[jax.Array] = None,
                   axis_name: str = SEQUENCE_AXIS,
                   causal: bool = True) -> jax.Array:
    """Attention over a sequence-sharded batch; call inside shard_map.

    q/k/v: local shards [B, S_local, H, D]; segment_ids: local int32
    [B, S_local] shard (tokens attend only within equal ids — a padded
    batch's attention_mask maps directly, pads = segment 0; the kv-shard's
    ids rotate around the ring with k/v). The local shard index along
    `axis_name` determines global positions (contiguous layout: shard i
    holds positions [i*S_local, (i+1)*S_local)).
    """
    ring_size = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    batch, s_local, num_heads, head_dim = q.shape
    scale = 1.0 / jnp.sqrt(head_dim).astype(jnp.float32)

    q_pos = my_idx * s_local + jnp.arange(s_local)  # global query positions

    acc = jnp.zeros((batch, s_local, num_heads, head_dim), jnp.float32)
    row_max = jnp.full((batch, num_heads, s_local), _NEG_INF, jnp.float32)
    row_sum = jnp.zeros((batch, num_heads, s_local), jnp.float32)

    has_segments = segment_ids is not None
    seg_kv0 = segment_ids if has_segments else \
        jnp.zeros((batch, s_local), jnp.int32)

    def body(step, carry):
        acc, row_max, row_sum, k_cur, v_cur, seg_cur = carry
        # shard that k_cur originated from
        src_idx = (my_idx - step) % ring_size
        k_pos = src_idx * s_local + jnp.arange(s_local)

        scores = _block_scores(q, k_cur, scale)  # [B,H,Sq,Sk]
        allowed = None
        if causal:
            allowed = (k_pos[None, :] <= q_pos[:, None])[None]
        if has_segments:
            same = (segment_ids[:, :, None] ==
                    seg_cur[:, None, :])  # [B, Sq, Sk]
            allowed = same if allowed is None else (allowed & same)
        if allowed is not None:
            scores = jnp.where(allowed[:, None], scores, _NEG_INF)

        blk_max = scores.max(axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[..., None])
        new_sum = row_sum * correction + probs.sum(axis=-1)
        blk_out = jnp.einsum("bhqk,bkhd->bqhd",
                             probs.astype(v_cur.dtype), v_cur
                             ).astype(jnp.float32)
        acc = acc * correction.transpose(0, 2, 1)[..., None] + blk_out

        # rotate k/v (+ their segment ids) to the next device; overlap
        # with the next step's compute
        perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        seg_next = jax.lax.ppermute(seg_cur, axis_name, perm) \
            if has_segments else seg_cur  # no dead collective without segs
        return (acc, new_max, new_sum, k_next, v_next, seg_next)

    carry = (acc, row_max, row_sum, k, v, seg_kv0)
    carry = jax.lax.fori_loop(0, ring_size, body, carry)
    acc, row_max, row_sum = carry[0], carry[1], carry[2]

    # fully-masked rows (can happen for the first queries under causal with
    # padding) keep sum==0; guard the divide
    denom = jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def sequence_sharded_call(body_fn, q: jax.Array, k: jax.Array, v: jax.Array,
                          segment_ids: Optional[jax.Array] = None,
                          mesh: Optional[Mesh] = None,
                          causal: bool = True) -> jax.Array:
    """Shared shard_map plumbing for context-parallel attention bodies
    (ring / Ulysses): shard the sequence dim over the 'sequence' axis and
    the batch over the batch axes, falling back to plain flash attention
    when the mesh has no usable sequence axis (or the shape doesn't fit —
    init passes batch=1, which is not divisible by the batch axes).

    `body_fn(q, k, v, segment_ids=..., axis_name=..., causal=...)` runs on
    local shards with `axis_name` in scope.
    """
    mesh = mesh or get_mesh()

    def _flash_fallback():
        from fengshen_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids)

    if mesh is None or SEQUENCE_AXIS not in mesh.shape or \
            mesh.shape[SEQUENCE_AXIS] == 1:
        return _flash_fallback()

    from fengshen_tpu.parallel.partition import _spec_fits
    spec = _spec_fits(P(BATCH_AXES, SEQUENCE_AXIS, None, None), mesh,
                      tuple(q.shape))
    if SEQUENCE_AXIS not in jax.tree_util.tree_leaves(tuple(spec)):
        return _flash_fallback()
    in_specs = (spec, spec, spec)
    args = (q, k, v)
    body = partial(body_fn, axis_name=SEQUENCE_AXIS, causal=causal)
    if segment_ids is None:
        body = partial(body, segment_ids=None)
    else:
        in_specs = in_specs + (P(*spec[:2]),)
        args = args + (segment_ids.astype(jnp.int32),)
    fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=spec,
                   check_vma=False)
    return fn(*args)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           segment_ids: Optional[jax.Array] = None,
                           mesh: Optional[Mesh] = None,
                           causal: bool = True) -> jax.Array:
    """shard_map wrapper: q/k/v globally [B, S, H, D], sequence dim sharded
    over the 'sequence' axis, batch over the batch axes; segment_ids
    int32 [B, S] (padded batches map their attention_mask here, so
    sequence parallelism no longer downgrades to dense under padding)."""
    return sequence_sharded_call(ring_attention, q, k, v,
                                 segment_ids=segment_ids, mesh=mesh,
                                 causal=causal)
