"""Ulysses-style all-to-all sequence parallelism.

The second context-parallel scheme next to ring attention (the reference
has neither — SURVEY.md §5.7). Instead of rotating k/v shards around a
ring, two `all_to_all` collectives re-shard the activations from
sequence-sharded [B, S/sp, H, D] to head-sharded [B, S, H/sp, D], run an
ordinary (flash) attention over the FULL sequence on each device, and
shard back (DeepSpeed-Ulysses, Jacobs et al. 2023 — public technique,
re-implemented here with XLA collectives over the ICI mesh).

Trade-off vs ring: Ulysses moves each activation token exactly twice
(a2a in, a2a out — O(S·H·D/sp) per device) and keeps the attention kernel
untouched (the fused Pallas flash kernel runs as-is on the gathered
sequence), but requires num_heads % sp == 0 and materializes the full-S
kv on each device, so per-device attention memory is O(S) rather than
ring's O(S/sp). `sequence_parallel_attention` auto-picks: Ulysses when
heads divide (kernel-friendly), ring otherwise or when
`prefer="ring"` (longest contexts).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from fengshen_tpu.compat import axis_size as _axis_size
import jax.numpy as jnp
from jax.sharding import Mesh

from fengshen_tpu.parallel.mesh import SEQUENCE_AXIS, get_mesh


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      segment_ids: Optional[jax.Array] = None,
                      axis_name: str = SEQUENCE_AXIS,
                      causal: bool = True) -> jax.Array:
    """Attention over a sequence-sharded batch; call inside shard_map.

    q/k/v: local shards [B, S_local, H, D] with contiguous sequence layout
    (shard i holds positions [i*S_local, (i+1)*S_local)) — the same
    contract as `ring_attention`. segment_ids: local int32 [B, S_local].
    Requires H % axis_size == 0.
    """
    from fengshen_tpu.ops.flash_attention import flash_attention

    sp = _axis_size(axis_name)
    num_heads = q.shape[2]
    if num_heads % sp:
        raise ValueError(
            f"ulysses needs num_heads ({num_heads}) divisible by the "
            f"sequence-parallel degree ({sp}); use ring attention instead")

    # [B, S/sp, H, D] -> [B, S, H/sp, D]: head-chunk j goes to device j,
    # received sequence chunks concatenate in device order = global order
    a2a_in = partial(jax.lax.all_to_all, axis_name=axis_name,
                     split_axis=2, concat_axis=1, tiled=True)
    qg, kg, vg = a2a_in(q), a2a_in(k), a2a_in(v)
    seg_g = None
    if segment_ids is not None:
        seg_g = jax.lax.all_gather(segment_ids, axis_name, axis=1,
                                   tiled=True)  # [B, S]

    out = flash_attention(qg, kg, vg, causal=causal, segment_ids=seg_g)

    # [B, S, H/sp, D] -> [B, S/sp, H, D]
    return jax.lax.all_to_all(out, axis_name=axis_name,
                              split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                              segment_ids: Optional[jax.Array] = None,
                              mesh: Optional[Mesh] = None,
                              causal: bool = True) -> jax.Array:
    """shard_map wrapper: q/k/v globally [B, S, H, D], sequence dim sharded
    over the 'sequence' axis, batch over the batch axes (shares the
    plumbing with `ring_attention_sharded`)."""
    from fengshen_tpu.ops.ring_attention import sequence_sharded_call
    return sequence_sharded_call(ulysses_attention, q, k, v,
                                 segment_ids=segment_ids, mesh=mesh,
                                 causal=causal)


def sequence_parallel_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                                segment_ids: Optional[jax.Array] = None,
                                mesh: Optional[Mesh] = None,
                                causal: bool = True,
                                prefer: str = "auto") -> jax.Array:
    """Context-parallel attention with scheme auto-selection.

    prefer: "auto" (Ulysses when num_heads divides the sequence degree —
    one fused kernel over the full sequence, 2 a2a hops; ring otherwise),
    "ring" (O(S/sp) per-device memory, any head count — the choice for
    the longest contexts), or "ulysses".
    """
    from fengshen_tpu.ops.ring_attention import ring_attention_sharded

    mesh = mesh or get_mesh()
    sp = mesh.shape.get(SEQUENCE_AXIS, 1) if mesh is not None else 1
    num_heads = q.shape[2]
    if prefer == "ring":
        use_ulysses = False
    elif prefer == "ulysses":
        use_ulysses = True
    elif prefer == "auto":
        use_ulysses = sp > 1 and num_heads % sp == 0
    else:
        raise ValueError(f"unknown prefer={prefer!r}")
    if use_ulysses:
        return ulysses_attention_sharded(q, k, v, segment_ids=segment_ids,
                                         mesh=mesh, causal=causal)
    return ring_attention_sharded(q, k, v, segment_ids=segment_ids,
                                  mesh=mesh, causal=causal)
