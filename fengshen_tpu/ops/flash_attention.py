"""Memory-efficient (flash-style) exact attention.

TPU-native replacement for the reference's flash-attention CUDA binding
(reference: fengshen/models/megatron/layers/flash_attention.py:107-185 wraps
flash_attn_cuda.fwd/bwd). Two tiers:

1. `blockwise_attention` — O(S) memory exact attention via online softmax
   over k/v blocks with `lax.scan`. Pure XLA: runs on TPU and on the CPU
   test backend, differentiable, and XLA fuses each block's
   matmul→rescale→matmul chain onto the MXU. Causal masking is computed
   per k-block from indices — no dense [Sq, Sk] bias is ever materialised.
2. On real TPU, `flash_attention` prefers the Pallas fused kernel
   (fengshen_tpu.ops.pallas.flash_attention) when shapes are tile-aligned,
   mirroring the reference's `is_kernel_available` dispatch
   (reference: fengshen/models/megatron/layers/fused_softmax.py:148-168).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        bias: Optional[jax.Array] = None,
                        causal: bool = False,
                        block_size: int = 512) -> jax.Array:
    """Online-softmax attention. q: [B, Sq, H, D], k/v: [B, Sk, H, D],
    bias broadcastable to [B, H, Sq, Sk]. Returns [B, Sq, H, D].

    Prefer `causal=True` over passing a causal bias: the mask is then
    computed per block from indices, keeping memory O(Sq·block) instead of
    O(Sq·Sk).
    """
    batch, q_len, num_heads, head_dim = q.shape
    k_len = k.shape[1]
    blk = min(block_size, k_len)
    pad = (blk - k_len % blk) % blk
    if pad:  # pad k/v to a block multiple; padding is masked by position
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if bias is not None:
            bias = jnp.broadcast_to(
                bias.astype(jnp.float32),
                bias.shape[:-2] + (q_len, k_len))
            bias = jnp.pad(bias, ((0, 0),) * (bias.ndim - 1) + ((0, pad),),
                           constant_values=_NEG_INF)
    padded_len = k_len + pad

    n_blocks = padded_len // blk
    scale = 1.0 / jnp.sqrt(head_dim).astype(jnp.float32)
    # global positions; q is assumed right-aligned with k (Sq suffix of Sk),
    # matching the KV-cache decode convention
    q_pos = jnp.arange(k_len - q_len, k_len)

    if bias is not None:
        bias = jnp.broadcast_to(
            bias.astype(jnp.float32),
            bias.shape[:-2] + (q_len, padded_len))
        bias_blocks = jnp.moveaxis(
            bias.reshape(bias.shape[:-1] + (n_blocks, blk)), -2, 0)
    k_blocks = jnp.moveaxis(
        k.reshape(batch, n_blocks, blk, num_heads, head_dim), 1, 0)
    v_blocks = jnp.moveaxis(
        v.reshape(batch, n_blocks, blk, num_heads, head_dim), 1, 0)
    blk_idx = jnp.arange(n_blocks)

    def step(carry, xs):
        acc, row_max, row_sum = carry
        if bias is not None:
            bi, k_blk, v_blk, b_blk = xs
        else:
            bi, k_blk, v_blk = xs
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        if bias is not None:
            scores = scores + b_blk
        k_pos = bi * blk + jnp.arange(blk)
        if causal:
            allowed = (k_pos[None, :] <= q_pos[:, None]) & \
                (k_pos[None, :] < k_len)
        else:
            allowed = jnp.broadcast_to(k_pos[None, :] < k_len, (q_len, blk))
        scores = jnp.where(allowed[None, None], scores, _NEG_INF)
        blk_max = scores.max(axis=-1)                       # [B,H,Sq]
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[..., None])
        # fully-masked blocks contribute nothing (probs underflow to 0 at
        # exp(_NEG_INF - max))
        new_sum = row_sum * correction + probs.sum(axis=-1)
        blk_out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v_blk.dtype),
                             v_blk).astype(jnp.float32)
        acc = acc * correction.transpose(0, 2, 1)[..., None] + blk_out
        return (acc, new_max, new_sum), None

    acc0 = jnp.zeros((batch, q_len, num_heads, head_dim), jnp.float32)
    max0 = jnp.full((batch, num_heads, q_len), _NEG_INF, jnp.float32)
    sum0 = jnp.zeros((batch, num_heads, q_len), jnp.float32)

    xs = (blk_idx, k_blocks, v_blocks)
    if bias is not None:
        xs = xs + (bias_blocks,)

    (acc, _, row_sum), _ = jax.lax.scan(step, (acc0, max0, sum0), xs)
    out = acc / jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    bias: Optional[jax.Array] = None,
                    dropout_rng=None, dropout_rate: float = 0.0,
                    deterministic: bool = True,
                    block_size: int = 512,
                    causal: bool = False) -> jax.Array:
    """Flash attention with kernel dispatch.

    Attention dropout is not supported on the flash path (same restriction
    as the reference's flash branch, which bypasses the softmax-dropout,
    reference: layers/transformer.py:270-279) — callers fall back to dense
    when dropout is active.
    """
    if not deterministic and dropout_rate > 0.0:
        raise ValueError("flash attention path does not support attention "
                         "dropout; use impl='dense'")
    if _pallas_eligible(q, k, v, bias, causal):
        from fengshen_tpu.ops.pallas.flash_attention import (
            pallas_flash_attention)
        return pallas_flash_attention(q, k, v, causal)
    return blockwise_attention(q, k, v, bias=bias, causal=causal,
                               block_size=block_size)


def _pallas_eligible(q, k, v, bias, causal) -> bool:
    """Kernel-eligibility check in the spirit of the reference's
    `FusedScaleMaskSoftmax.is_kernel_available`
    (reference: layers/fused_softmax.py:148-168)."""
    if bias is not None:
        return False
    if jax.default_backend() != "tpu":
        return False
    _, q_len, _, head_dim = q.shape
    k_len = k.shape[1]
    return (head_dim % 128 == 0 and q_len % 128 == 0 and k_len % 128 == 0)
