"""Memory-efficient (flash-style) exact attention.

TPU-native replacement for the reference's flash-attention CUDA binding
(reference: fengshen/models/megatron/layers/flash_attention.py:107-185 wraps
flash_attn_cuda.fwd/bwd). Two tiers:

1. `blockwise_attention` — O(S) memory exact attention via online softmax
   over k/v blocks with `lax.scan`. Pure XLA: runs on TPU and on the CPU
   test backend, differentiable, and XLA fuses each block's
   matmul→rescale→matmul chain onto the MXU. Causal masking is computed
   per k-block from indices — no dense [Sq, Sk] bias is ever materialised.
2. On real TPU, `flash_attention` prefers the Pallas fused kernel
   (fengshen_tpu.ops.pallas.flash_attention) when shapes are tile-aligned,
   mirroring the reference's `is_kernel_available` dispatch
   (reference: fengshen/models/megatron/layers/fused_softmax.py:148-168).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        bias: Optional[jax.Array] = None,
                        causal: bool = False,
                        block_size: int = 512,
                        q_segment_ids: Optional[jax.Array] = None,
                        kv_segment_ids: Optional[jax.Array] = None
                        ) -> jax.Array:
    """Online-softmax attention. q: [B, Sq, H, D], k/v: [B, Sk, H, D],
    bias broadcastable to [B, H, Sq, Sk]; segment ids int32 [B, S] (tokens
    attend only within equal ids). Returns [B, Sq, H, D].

    Prefer `causal=True` over passing a causal bias: the mask is then
    computed per block from indices, keeping memory O(Sq·block) instead of
    O(Sq·Sk).
    """
    batch, q_len, num_heads, head_dim = q.shape
    k_len = k.shape[1]
    blk = min(block_size, k_len)
    pad = (blk - k_len % blk) % blk
    if pad:  # pad k/v to a block multiple; padding is masked by position
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if bias is not None:
            bias = jnp.broadcast_to(
                bias.astype(jnp.float32),
                bias.shape[:-2] + (q_len, k_len))
            bias = jnp.pad(bias, ((0, 0),) * (bias.ndim - 1) + ((0, pad),),
                           constant_values=_NEG_INF)
    if kv_segment_ids is not None and (pad or kv_segment_ids.shape[1] <
                                       k_len + pad):
        kv_segment_ids = jnp.pad(
            kv_segment_ids, ((0, 0), (0, k_len + pad -
                                      kv_segment_ids.shape[1])),
            constant_values=-1)  # -1 never equals a real segment id
    padded_len = k_len + pad

    n_blocks = padded_len // blk
    scale = 1.0 / jnp.sqrt(head_dim).astype(jnp.float32)
    # global positions; q is assumed right-aligned with k (Sq suffix of Sk),
    # matching the KV-cache decode convention
    q_pos = jnp.arange(k_len - q_len, k_len)

    if bias is not None:
        bias = jnp.broadcast_to(
            bias.astype(jnp.float32),
            bias.shape[:-2] + (q_len, padded_len))
        bias_blocks = jnp.moveaxis(
            bias.reshape(bias.shape[:-1] + (n_blocks, blk)), -2, 0)
    k_blocks = jnp.moveaxis(
        k.reshape(batch, n_blocks, blk, num_heads, head_dim), 1, 0)
    v_blocks = jnp.moveaxis(
        v.reshape(batch, n_blocks, blk, num_heads, head_dim), 1, 0)
    if kv_segment_ids is not None:
        kv_seg_blocks = jnp.moveaxis(
            kv_segment_ids.reshape(batch, n_blocks, blk), 1, 0)
    blk_idx = jnp.arange(n_blocks)

    def step(carry, xs):
        acc, row_max, row_sum = carry
        seg_blk = None
        if bias is not None and kv_segment_ids is not None:
            bi, k_blk, v_blk, b_blk, seg_blk = xs
        elif bias is not None:
            bi, k_blk, v_blk, b_blk = xs
        elif kv_segment_ids is not None:
            bi, k_blk, v_blk, seg_blk = xs
        else:
            bi, k_blk, v_blk = xs
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        if bias is not None:
            scores = scores + b_blk
        k_pos = bi * blk + jnp.arange(blk)
        if causal:
            allowed = (k_pos[None, :] <= q_pos[:, None]) & \
                (k_pos[None, :] < k_len)
        else:
            allowed = jnp.broadcast_to(k_pos[None, :] < k_len, (q_len, blk))
        allowed = jnp.broadcast_to(allowed[None, None],
                                   (batch, 1, q_len, blk))
        if seg_blk is not None:
            same = (q_segment_ids[:, :, None] ==
                    seg_blk[:, None, :])  # [B, Sq, blk]
            allowed = allowed & same[:, None]
        scores = jnp.where(allowed, scores, _NEG_INF)
        blk_max = scores.max(axis=-1)                       # [B,H,Sq]
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[..., None])
        # fully-masked blocks contribute nothing (probs underflow to 0 at
        # exp(_NEG_INF - max))
        new_sum = row_sum * correction + probs.sum(axis=-1)
        blk_out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v_blk.dtype),
                             v_blk).astype(jnp.float32)
        acc = acc * correction.transpose(0, 2, 1)[..., None] + blk_out
        return (acc, new_max, new_sum), None

    acc0 = jnp.zeros((batch, q_len, num_heads, head_dim), jnp.float32)
    max0 = jnp.full((batch, num_heads, q_len), _NEG_INF, jnp.float32)
    sum0 = jnp.zeros((batch, num_heads, q_len), jnp.float32)

    xs = (blk_idx, k_blocks, v_blocks)
    if bias is not None:
        xs = xs + (bias_blocks,)
    if kv_segment_ids is not None:
        xs = xs + (kv_seg_blocks,)

    (acc, _, row_sum), _ = jax.lax.scan(step, (acc0, max0, sum0), xs)
    out = acc / jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    bias: Optional[jax.Array] = None,
                    dropout_rng=None, dropout_rate: float = 0.0,
                    deterministic: bool = True,
                    block_size: int = 512,
                    causal: bool = False,
                    segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Flash attention with kernel dispatch.

    `segment_ids`: int32 [B, S] (or a (q_ids, kv_ids) tuple) — tokens attend
    only within equal ids. A padded batch's attention_mask maps directly
    (pads become segment 0), which keeps padded SFT batches on the fused
    kernel instead of the dense O(S²) path.

    Attention dropout is not supported on the flash path (same restriction
    as the reference's flash branch, which bypasses the softmax-dropout,
    reference: layers/transformer.py:270-279) — callers fall back to dense
    when dropout is active.
    """
    if not deterministic and dropout_rate > 0.0:
        raise ValueError("flash attention path does not support attention "
                         "dropout; use impl='dense'")
    if isinstance(segment_ids, (tuple, list)):
        q_seg, kv_seg = segment_ids
    else:
        q_seg = kv_seg = segment_ids
    if q_seg is not None:
        q_seg = q_seg.astype(jnp.int32)
        kv_seg = kv_seg.astype(jnp.int32)
    if _pallas_eligible(q, k, v, bias, causal):
        from fengshen_tpu.ops.pallas.flash_attention import (
            pallas_flash_attention)
        return pallas_flash_attention(q, k, v, q_seg, kv_seg, causal)
    if k.shape[2] != q.shape[2]:  # GQA fallback: repeat for blockwise
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return blockwise_attention(q, k, v, bias=bias, causal=causal,
                               block_size=block_size,
                               q_segment_ids=q_seg, kv_segment_ids=kv_seg)


def _pallas_eligible(q, k, v, bias, causal) -> bool:
    """Kernel-eligibility check in the spirit of the reference's
    `FusedScaleMaskSoftmax.is_kernel_available`
    (reference: layers/fused_softmax.py:148-168). GQA (fewer KV heads)
    is kernel-native — the grid index maps read each KV head once per
    group — as long as the head counts divide."""
    if bias is not None:
        return False
    from fengshen_tpu.ops.pallas import probe
    if not probe().pallas_tpu:
        return False
    _, q_len, n_heads, head_dim = q.shape
    k_len, kv_heads = k.shape[1], k.shape[2]
    if n_heads % kv_heads != 0:
        return False
    return (head_dim % 128 == 0 and q_len % 128 == 0 and k_len % 128 == 0)
