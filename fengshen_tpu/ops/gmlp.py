"""gMLP blocks (spatial-gating MLP, optionally with tiny attention).

Reference: fengshen/models/megatron/layers/gmlp.py:28-141 —
`TinyAttention` (single-head attention over the gate path),
`SpatialGatingUnit` (split-channel gate with a learned causal S×S spatial
projection, zero-init weight / ones-init bias so the block starts as
identity), `GMLPBlock` (norm → in-proj to 2*ff → activation → SGU →
out-proj).

TPU-native differences: batch-major [B, S, D] layout throughout (the
reference is seq-major [S, B, D] with transposes); the spatial projection
is a single fp32 einsum over the sequence axis that XLA maps onto the MXU;
causality is enforced by masking the S×S weight with a lower-triangular
matrix inside the forward (static shapes, no data-dependent slicing); TP
sharding comes from partition rules on the in/out projections rather than
Column/RowParallelLinear classes.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.masks import causal_mask
from fengshen_tpu.ops.norms import LayerNorm


class TinyAttention(nn.Module):
    """Single-head attention on the (2*ff)-wide gate input
    (reference: gmlp.py:28-50). Delegates the masked softmax to
    `dot_product_attention` (fp32 scores/softmax, shared numerics)."""

    d_attn: int
    d_ff: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array,
                 attention_mask: Optional[jax.Array] = None) -> jax.Array:
        qkv = nn.Dense(3 * self.d_attn, dtype=self.dtype, name="proj_qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        out = dot_product_attention(
            q[:, :, None], k[:, :, None], v[:, :, None],
            mask=attention_mask)[:, :, 0]
        return nn.Dense(self.d_ff, dtype=self.dtype, name="proj_ffn")(out)


class SpatialGatingUnit(nn.Module):
    """Split-channel spatial gate (reference: gmlp.py:53-90).

    The input [B, S, 2*ff] splits into residual/gate halves; the gate is
    normed, mixed across the sequence axis by a learned S×S projection
    (zero-init weight, ones bias → identity gate at init), optionally
    augmented by tiny attention on the full input, then multiplied with
    the residual half.
    """

    d_ff: int
    max_seq_len: int
    d_attn: Optional[int] = None
    causal: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array,
                 attention_mask: Optional[jax.Array] = None) -> jax.Array:
        seq_len = x.shape[1]
        res, gate = jnp.split(x, 2, axis=-1)
        gate = LayerNorm(dtype=self.dtype, name="norm")(gate)

        # learned spatial mixing weight over positions (fp32 master copy;
        # zero/ones init as in reference gmlp.py:69-70)
        weight = self.param("spatial_weight", nn.initializers.zeros,
                            (self.max_seq_len, self.max_seq_len), jnp.float32)
        bias = self.param("spatial_bias", nn.initializers.ones,
                          (self.max_seq_len,), jnp.float32)
        w = weight[:seq_len, :seq_len]
        if self.causal:
            w = jnp.tril(w)  # output position n sees inputs m <= n
        gate = (jnp.einsum("bmd,nm->bnd", gate.astype(jnp.float32), w)
                + bias[:seq_len, None]).astype(x.dtype)

        if self.d_attn is not None:
            if self.causal:
                # causality must not depend on the caller's mask — AND the
                # causal constraint into whatever (padding) mask was given
                # (reference gmlp.py passes the global ltor mask via mask_fn)
                cmask = causal_mask(seq_len)
                attention_mask = cmask if attention_mask is None \
                    else (attention_mask & cmask)
            gate = gate + TinyAttention(
                d_attn=self.d_attn, d_ff=self.d_ff, dtype=self.dtype,
                name="attn")(x, attention_mask)
        return gate * res


class GMLPBlock(nn.Module):
    """Pre-norm gMLP block (reference: gmlp.py:93-141): norm → Dense to
    2*ff → activation → SpatialGatingUnit → Dense to hidden. Pass
    `d_attn` to get the "amlp" variant (reference: gmlp.py:117-120)."""

    hidden_size: int
    intermediate_size: int
    max_seq_len: int
    activation: str = "gelu"
    d_attn: Optional[int] = None
    causal: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array,
                 attention_mask: Optional[jax.Array] = None) -> jax.Array:
        h = LayerNorm(dtype=self.dtype, name="norm")(x)
        h = nn.Dense(2 * self.intermediate_size, dtype=self.dtype,
                     name="input_linear")(h)
        h = get_activation(self.activation)(h)
        h = SpatialGatingUnit(
            d_ff=self.intermediate_size, max_seq_len=self.max_seq_len,
            d_attn=self.d_attn, causal=self.causal, dtype=self.dtype,
            name="sgu")(h, attention_mask)
        return nn.Dense(self.hidden_size, dtype=self.dtype,
                        name="output_linear")(h)


# TP partition rules for the gMLP projections (column-shard the widening
# proj, row-shard the narrowing proj — same layout as ParallelMLP).
GMLP_PARTITION_RULES = (
    (r".*input_linear/kernel", ("embed", "mlp")),
    (r".*output_linear/kernel", ("mlp", "embed")),
    (r".*spatial_weight", (None, None)),
)
