"""Scaled dot-product attention.

Replaces the reference's attention compute stack: baddbmm QK^T with a
preallocated buffer + FusedScaleMaskSoftmax CUDA kernel + context bmm
(reference: fengshen/models/megatron/layers/transformer.py:307-456 and
layers/fused_softmax.py:24-205), and the flash-attention CUDA binding
(reference: layers/flash_attention.py:107-185).

On TPU the dense path is a single fused XLA HLO chain (matmul→scale→mask→
softmax→matmul hits the MXU with the softmax fused in between); the
`impl="flash"` path dispatches to the Pallas flash kernel in
fengshen_tpu.ops.flash_attention for long sequences, and `impl="ring"` to
sequence-parallel ring attention in fengshen_tpu.ops.ring_attention.

Numerics: softmax statistics are always computed in fp32, mirroring the
reference's fp32-upcast fallback rule (reference:
layers/fused_softmax.py:184-200) so loss curves are comparable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _dense_attention(q, k, v, bias, dropout_rng, dropout_rate, deterministic):
    """q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; bias broadcastable to
    [B, H, Sq, Sk]. Returns [B, Sq, H, D]."""
    head_dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(head_dim).astype(jnp.float32)
    # [B, H, Sq, Sk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1)
    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          bias: Optional[jax.Array] = None,
                          mask: Optional[jax.Array] = None,
                          dropout_rng: Optional[jax.Array] = None,
                          dropout_rate: float = 0.0,
                          deterministic: bool = True,
                          impl: str = "dense",
                          sparse_layout=None,
                          sparse_block_size: int = 128,
                          segment_ids: Optional[jax.Array] = None
                          ) -> jax.Array:
    """Attention entry point with per-layer impl dispatch.

    `impl` mirrors the reference's per-layer `attention_config` selection of
    dense / flash / sparse kernels
    (reference: layers/transformer.py:259-268).

    `impl="sparse"` takes `sparse_layout` — a STATIC (numpy) [nQ, nK] bool
    block-presence matrix with `sparse_block_size` tokens per block (build
    one with the `*_block_layout` helpers in fengshen_tpu.ops.masks) — and
    runs the Pallas block-sparse kernel when shapes are tile-aligned,
    skipping absent blocks entirely; otherwise it falls back to
    dense-with-expanded-mask (the layouts are also expressible as `mask`,
    which runs on any backend).

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; mask: bool broadcastable to
    [B, H, Sq, Sk] (True = attend); bias: additive, same broadcast.
    """
    if impl == "sparse" and sparse_layout is not None:
        import numpy as np

        from fengshen_tpu.ops.pallas import probe
        layout = np.asarray(sparse_layout)
        blk = sparse_block_size
        eligible = (
            bias is None and mask is None and
            (deterministic or dropout_rate == 0.0) and
            probe().pallas_tpu and
            q.shape[1] % blk == 0 and k.shape[1] % blk == 0 and
            blk % 128 == 0 and q.shape[-1] % 128 == 0 and
            layout.shape == (q.shape[1] // blk, k.shape[1] // blk))
        if eligible:
            from fengshen_tpu.ops.pallas.block_sparse_attention import (
                block_sparse_attention)
            return block_sparse_attention(q, k, v, layout, blk)
        # fall back: expand the block layout to a dense mask
        expanded = jnp.asarray(
            np.kron(layout, np.ones((blk, blk), dtype=bool)))
        mask = expanded[None, None] if mask is None else \
            (mask & expanded[None, None])

    if segment_ids is not None and impl in ("dense", "sparse"):
        # dense path honors segments as an explicit mask
        seg_mask = (segment_ids[:, None, None, :] ==
                    segment_ids[:, None, :, None])
        mask = seg_mask if mask is None else (mask & seg_mask)

    if mask is not None:
        neg = jnp.asarray(-1e9, dtype=jnp.float32)
        mask_bias = jnp.where(mask, 0.0, neg)
        bias = mask_bias if bias is None else bias + mask_bias

    if impl in ("dense", "sparse"):
        return _dense_attention(q, k, v, bias, dropout_rng, dropout_rate,
                                deterministic)
    if impl == "flash":
        from fengshen_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, bias=bias,
                               dropout_rng=dropout_rng,
                               dropout_rate=dropout_rate,
                               deterministic=deterministic,
                               segment_ids=segment_ids)
    if impl in ("ring", "ulysses", "sequence"):
        if bias is not None:
            raise ValueError(f"impl={impl!r} supports causal/segment "
                             "masking only; express other patterns via "
                             "impl='dense'")
        from fengshen_tpu.ops.ulysses_attention import (
            sequence_parallel_attention)
        prefer = {"ring": "ring", "ulysses": "ulysses",
                  "sequence": "auto"}[impl]
        return sequence_parallel_attention(q, k, v, segment_ids=segment_ids,
                                           causal=True, prefer=prefer)
    raise ValueError(f"unknown attention impl {impl!r}")
