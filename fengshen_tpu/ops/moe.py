"""Mixture-of-Experts (Switch-style) with expert parallelism.

No reference equivalent — the reference framework has no MoE. This is a
beyond-reference, TPU-native capability: experts live as stacked
[E, ...] parameter tables sharded over the 'expert' mesh axis, tokens are
dispatched with the static-shape capacity formulation (Shazeer et al.
Mesh-TF / Fedus et al. Switch Transformer — public techniques,
re-implemented on einsum + GSPMD), and the compiler inserts the
token all-to-all from the sharding constraints instead of hand-coded
collectives.

Shapes are fully static (capacity C per expert; overflow tokens drop and
pass through the residual), so the whole layer jits into one program —
no data-dependent gather/scatter.
"""

from __future__ import annotations

import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from fengshen_tpu.parallel.mesh import BATCH_AXES, EXPERT_AXIS
from fengshen_tpu.parallel.partition import with_sharding_constraint

#: partition rules for the stacked expert tables ([E, in, out]) and router
MOE_PARTITION_RULES: list[tuple[str, P]] = [
    (r".*router/kernel", P(None, None)),
    (r".*experts_(gate|up)", P(EXPERT_AXIS, None, "tensor")),
    (r".*experts_down", P(EXPERT_AXIS, "tensor", None)),
]


def load_balancing_loss(router_probs: jax.Array,
                        expert_index: jax.Array,
                        num_experts: int,
                        token_mask: jax.Array | None = None) -> jax.Array:
    """Switch aux loss: E * sum_e f_e * P_e, minimized at uniform routing
    (Switch Transformer eq. 4). router_probs [T, E] fp32; expert_index
    [T] int32; token_mask [T] (1 = real token) excludes pads from the
    routing statistics."""
    onehot = jax.nn.one_hot(expert_index, num_experts, dtype=jnp.float32)
    if token_mask is None:
        f = jnp.mean(onehot, axis=0)                            # [E]
        p = jnp.mean(router_probs, axis=0)                      # [E]
    else:
        tm = token_mask.astype(jnp.float32)[:, None]
        denom = jnp.maximum(tm.sum(), 1.0)
        f = (onehot * tm).sum(axis=0) / denom
        p = (router_probs * tm).sum(axis=0) / denom
    return num_experts * jnp.sum(f * p)


class SwitchMoE(nn.Module):
    """Top-1 (switch) routed SwiGLU expert MLP, drop-in for a dense MLP.

    Returns (output, aux_loss). The aux loss is also sowed under
    ("losses", "moe_aux_loss") so deeply nested callers can collect it
    with `mutable=["losses"]` instead of threading it manually.
    """

    hidden_size: int
    intermediate_size: int
    num_experts: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    router_jitter: float = 0.0  # train-time multiplicative jitter

    @nn.compact
    def __call__(self, x: jax.Array, token_mask: jax.Array | None = None,
                 deterministic: bool = True
                 ) -> Tuple[jax.Array, jax.Array]:
        """x: [B, S, H]; token_mask: [B, S] (1 = real token) — pads are
        excluded from dispatch (they neither consume expert capacity nor
        skew the load-balance statistics) and output zeros, which the
        caller's residual carries through."""
        batch, seq, hidden = x.shape
        E = self.num_experts
        tokens = batch * seq
        capacity = max(1, int(math.ceil(
            tokens / E * self.capacity_factor)))

        xt = x.reshape(tokens, hidden)
        tm = None if token_mask is None else \
            token_mask.reshape(tokens).astype(jnp.float32)

        # --- router (fp32 for a stable softmax) ---
        router_in = xt
        if self.router_jitter > 0.0 and not deterministic:
            key = self.make_rng("dropout")
            router_in = router_in * jax.random.uniform(
                key, router_in.shape, router_in.dtype,
                1.0 - self.router_jitter, 1.0 + self.router_jitter)
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32,
                          name="router")(router_in.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)                 # [T, E]
        gate = probs.max(axis=-1)                               # [T]
        expert_index = probs.argmax(axis=-1).astype(jnp.int32)  # [T]

        aux = load_balancing_loss(probs, expert_index, E, token_mask=tm)
        self.sow("losses", "moe_aux_loss", aux)

        # --- static-capacity dispatch (Mesh-TF formulation) ---
        onehot = jax.nn.one_hot(expert_index, E, dtype=jnp.float32)
        if tm is not None:
            onehot = onehot * tm[:, None]  # pads claim no capacity slot
        # position of each token within its expert's queue
        pos = jnp.einsum("te,te->t", jnp.cumsum(onehot, axis=0) - 1.0,
                         onehot).astype(jnp.int32)              # [T]
        keep = pos < capacity
        dispatch = (onehot * keep[:, None].astype(jnp.float32))[..., None] \
            * jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                             dtype=jnp.float32)[:, None, :]     # [T, E, C]
        combine = dispatch * gate[:, None, None]                # [T, E, C]

        expert_in = jnp.einsum("tec,th->ech", dispatch,
                               xt.astype(jnp.float32)
                               ).astype(self.dtype)             # [E, C, H]
        expert_in = with_sharding_constraint(
            expert_in, P(EXPERT_AXIS, None, None))

        # --- per-expert SwiGLU over stacked tables ---
        init = nn.initializers.normal(0.02)
        w_gate = self.param("experts_gate", init,
                            (E, hidden, self.intermediate_size),
                            self.param_dtype)
        w_up = self.param("experts_up", init,
                          (E, hidden, self.intermediate_size),
                          self.param_dtype)
        w_down = self.param("experts_down", init,
                            (E, self.intermediate_size, hidden),
                            self.param_dtype)
        g = jnp.einsum("ech,ehf->ecf", expert_in,
                       w_gate.astype(self.dtype))
        u = jnp.einsum("ech,ehf->ecf", expert_in,
                       w_up.astype(self.dtype))
        h = nn.silu(g) * u
        h = with_sharding_constraint(h, P(EXPERT_AXIS, None, "tensor"))
        expert_out = jnp.einsum("ecf,efh->ech", h,
                                w_down.astype(self.dtype))      # [E, C, H]

        # --- combine (dropped tokens get zeros → caller's residual) ---
        out = jnp.einsum("tec,ech->th", combine,
                         expert_out.astype(jnp.float32))
        out = out.reshape(batch, seq, hidden).astype(x.dtype)
        out = with_sharding_constraint(out, P(BATCH_AXES, "sequence", None))
        return out, aux
