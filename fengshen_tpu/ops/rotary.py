"""Rotary position embeddings.

Reference: fengshen/models/megatron/layers/positional_embeddings.py:38-88
(`RotaryEmbedding` with cached cos/sin, `apply_rotary_pos_emb` gathered by
position_ids, partial-rotary via `rotary_pct`,
layers/transformer.py:240-257). Implemented as pure functions — the cos/sin
table is computed inside jit where XLA constant-folds / fuses it; no mutable
cache needed.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def rotary_cos_sin(positions: jax.Array, dim: int, base: float = 10000.0,
                   dtype=jnp.float32) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for integer `positions` [..., S] over rotary dim `dim`.

    Returns (cos, sin) of shape [..., S, dim] using the half-rotation
    (rotate_half) convention — the same layout the reference uses
    (reference: positional_embeddings.py:70-76).
    """
    inv_freq = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., S, dim/2]
    angles = jnp.concatenate([angles, angles], axis=-1)           # [..., S, dim]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def _rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(q: jax.Array, k: jax.Array,
                         positions: jax.Array,
                         rotary_dim: Optional[int] = None,
                         base: float = 10000.0) -> tuple[jax.Array, jax.Array]:
    """Apply RoPE to q/k of shape [B, S, H, D] with positions [B, S].

    `rotary_dim < D` gives the partial-rotary behaviour of the reference's
    `rotary_pct` (reference: layers/transformer.py:240-257: split into
    rot/pass components, rotate, re-concat).
    """
    head_dim = q.shape[-1]
    rotary_dim = rotary_dim or head_dim
    cos, sin = rotary_cos_sin(positions, rotary_dim, base=base, dtype=q.dtype)
    cos = cos[:, :, None, :]  # [B, S, 1, rotary_dim]
    sin = sin[:, :, None, :]

    def rot(x):
        if rotary_dim == head_dim:
            return x * cos + _rotate_half(x) * sin
        x_rot, x_pass = x[..., :rotary_dim], x[..., rotary_dim:]
        x_rot = x_rot * cos + _rotate_half(x_rot) * sin
        return jnp.concatenate([x_rot, x_pass], axis=-1)

    return rot(q), rot(k)
