"""Attention mask / sparsity layouts.

Covers two reference surfaces:
- dense causal/padding mask builders
  (reference: fengshen/models/megatron/layers/utils.py:26-63
  `get_attn_mask`/`get_ltor_masks_and_position_ids`);
- the DeepSpeed block-sparse layouts (fixed, variable, local sliding window,
  bigbird, bslongformer) that the reference configures via
  `configure_sparse_attention`
  (reference: fengshen/models/megatron/layers/utils.py:187-289).

All masks here are boolean [.., Sq, Sk] with True = "may attend"; they are
turned into additive biases by `make_attention_bias`. Dense-with-mask is the
baseline implementation; the Pallas splash-attention path consumes the same
layouts as block masks.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def causal_mask(q_len: int, k_len: Optional[int] = None) -> jax.Array:
    """Lower-triangular [Sq, Sk] (reference: layers/utils.py:26-35)."""
    k_len = k_len or q_len
    q_pos = jnp.arange(k_len - q_len, k_len)[:, None]
    k_pos = jnp.arange(k_len)[None, :]
    return k_pos <= q_pos


def sliding_window_mask(q_len: int, window: int,
                        k_len: Optional[int] = None,
                        causal: bool = True) -> jax.Array:
    """Local sliding-window layout (reference: DeepSpeed
    LocalSlidingWindowSparsityConfig via layers/utils.py:253-259; also the
    Longformer family's window attention,
    reference: fengshen/models/longformer/modeling_longformer.py)."""
    k_len = k_len or q_len
    q_pos = jnp.arange(k_len - q_len, k_len)[:, None]
    k_pos = jnp.arange(k_len)[None, :]
    diff = q_pos - k_pos
    if causal:
        return (diff >= 0) & (diff < window)
    return jnp.abs(diff) < window


def bigbird_block_layout(seq_len: int, block: int, num_random_blocks: int,
                         num_global_blocks: int, num_window_blocks: int,
                         seed: int = 0, causal: bool = False) -> np.ndarray:
    """BigBird block-presence matrix [n, n] (numpy bool, STATIC) — the form
    the Pallas block-sparse kernel consumes directly."""
    assert seq_len % block == 0, "seq_len must be a multiple of block"
    n = seq_len // block
    rng = np.random.RandomState(seed)
    layout = np.zeros((n, n), dtype=bool)
    # window
    for off in range(-(num_window_blocks // 2), num_window_blocks // 2 + 1):
        idx = np.arange(max(0, -off), min(n, n - off))
        layout[idx, idx + off] = True
    # global rows+cols
    g = num_global_blocks
    layout[:g, :] = True
    layout[:, :g] = True
    # random per row
    for i in range(n):
        choices = rng.choice(n, size=min(num_random_blocks, n), replace=False)
        layout[i, choices] = True
    if causal:
        layout &= np.tril(np.ones((n, n), dtype=bool))
    return layout


def bigbird_mask(seq_len: int, block: int, num_random_blocks: int,
                 num_global_blocks: int, num_window_blocks: int,
                 seed: int = 0, causal: bool = False) -> jax.Array:
    """BigBird layout: global + window + random blocks
    (reference: DeepSpeed BigBirdSparsityConfig via layers/utils.py:260-267).
    Static (trace-time) construction — the layout is a compile-time constant,
    as block-sparse layouts must be for XLA.
    """
    layout = bigbird_block_layout(seq_len, block, num_random_blocks,
                                  num_global_blocks, num_window_blocks,
                                  seed, causal)
    return jnp.asarray(np.kron(layout, np.ones((block, block), dtype=bool)))


def longformer_block_layout(seq_len: int, block: int, num_window_blocks: int,
                            global_block_indices: tuple[int, ...] = (0,),
                            causal: bool = False) -> np.ndarray:
    """BSLongformer block-presence matrix [n, n] (numpy bool, STATIC)."""
    assert seq_len % block == 0
    n = seq_len // block
    layout = np.zeros((n, n), dtype=bool)
    for off in range(-(num_window_blocks // 2), num_window_blocks // 2 + 1):
        idx = np.arange(max(0, -off), min(n, n - off))
        layout[idx, idx + off] = True
    for gi in global_block_indices:
        layout[gi, :] = True
        layout[:, gi] = True
    if causal:
        layout &= np.tril(np.ones((n, n), dtype=bool))
    return layout


def longformer_mask(seq_len: int, block: int, num_window_blocks: int,
                    global_block_indices: tuple[int, ...] = (0,),
                    causal: bool = False) -> jax.Array:
    """BSLongformer layout: sliding window + designated global blocks
    (reference: DeepSpeed BSLongformerSparsityConfig via
    layers/utils.py:268-275)."""
    layout = longformer_block_layout(seq_len, block, num_window_blocks,
                                     global_block_indices, causal)
    return jnp.asarray(np.kron(layout, np.ones((block, block), dtype=bool)))


def fixed_block_layout(seq_len: int, block: int, num_local_blocks: int,
                       num_global_blocks: int = 1,
                       causal: bool = True) -> np.ndarray:
    """Fixed-sparsity block-presence matrix [n, n] (numpy bool, STATIC)."""
    assert seq_len % block == 0
    n = seq_len // block
    layout = np.zeros((n, n), dtype=bool)
    stride = num_local_blocks
    for i in range(n):
        blk_start = (i // stride) * stride
        layout[i, blk_start:i + 1] = True          # local window
        layout[i, stride - num_global_blocks::stride] = True  # global cols
    if causal:
        layout &= np.tril(np.ones((n, n), dtype=bool))
    else:
        layout |= layout.T
    return layout


def fixed_sparsity_mask(seq_len: int, block: int, num_local_blocks: int,
                        num_global_blocks: int = 1,
                        causal: bool = True) -> jax.Array:
    """Fixed layout à la Sparse Transformers: local stripes + periodic global
    columns (reference: DeepSpeed FixedSparsityConfig via
    layers/utils.py:236-244)."""
    layout = fixed_block_layout(seq_len, block, num_local_blocks,
                                num_global_blocks, causal)
    return jnp.asarray(np.kron(layout, np.ones((block, block), dtype=bool)))


def make_attention_bias(mask: Optional[jax.Array],
                        dtype=jnp.float32,
                        neg: float = -1e9) -> Optional[jax.Array]:
    """bool mask → additive bias (True→0, False→-inf-ish). The fp32-sized
    negative mirrors the reference's mask-fill value handling in its softmax
    fallback (reference: layers/fused_softmax.py:184-200)."""
    if mask is None:
        return None
    return jnp.where(mask, 0.0, neg).astype(dtype)
