"""Vocab-parallel embedding lookup.

The reference solves multi-rank embedding with ``VocabParallelEmbedding``:
each TP rank holds a vocab slice, out-of-range ids are masked to zero, and an
allreduce sums the partial lookups
(reference: fengshen/models/megatron/mpu/layers.py:55-130).

Under GSPMD the equivalent hazard shows up differently: a plain ``take`` on a
vocab-sharded table is a ``gather`` that the SPMD partitioner cannot shard —
it falls back to *involuntary full rematerialization*, i.e. every step
all-gathers the whole table (visible as spmd_partitioner.cc warnings in the
8-device dryrun). The TPU-native fix is the iota/one-hot matmul: encode ids
as a one-hot over the vocab and contract with the table on the MXU. The
contraction dim carries the vocab sharding, so GSPMD partitions it like any
tensor-parallel matmul (partial products + psum over ``tensor``) — the same
collective structure as the reference's mask+allreduce, with the mask fused
into the matmul. The backward becomes a matmul too (no scatter-add).

Single-device / unsharded-vocab paths keep the plain ``take`` — the one-hot
matmul costs 2·B·S·V·H FLOPs and only pays for itself when it removes the
table all-gather.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from fengshen_tpu.parallel.mesh import (BATCH_AXES, SEQUENCE_AXIS,
                                        TENSOR_AXIS, get_mesh)

#: nn.Embed's default initializer, kept so VocabParallelEmbed is a drop-in
default_embed_init = nn.initializers.variance_scaling(
    1.0, "fan_in", "normal", out_axis=0)


def vocab_shards(num_embeddings: int, vocab_axis: str = TENSOR_AXIS) -> int:
    """How many ways the vocab dim of an embedding table is sharded under
    the installed mesh (1 = unsharded, mirrors partition._spec_fits's
    drop-if-indivisible rule)."""
    mesh = get_mesh()
    if mesh is None or vocab_axis not in mesh.shape:
        return 1
    n = int(mesh.shape[vocab_axis])
    if n <= 1 or num_embeddings % n != 0:
        return 1
    # Inside a shard_map stage where the vocab axis is Manual the lookup is
    # already rank-local; the one-hot trick must not fire there.
    try:
        abstract = jax.sharding.get_abstract_mesh()
        if abstract is not None and abstract.axis_names:
            for name, t in zip(abstract.axis_names, abstract.axis_types):
                if name == vocab_axis and "Manual" in str(t):
                    return 1
    except Exception:  # pragma: no cover
        pass
    return n


def embed_lookup(table: jax.Array, ids: jax.Array,
                 vocab_axis: str = TENSOR_AXIS) -> jax.Array:
    """table[ids] that stays sharded when the vocab dim is mesh-sharded.

    ``table`` is [V, H]; ``ids`` any integer shape. Dispatches between a
    plain take (unsharded vocab) and the one-hot MXU matmul (sharded vocab,
    reference-collective-equivalent: mpu/layers.py:55-130).
    """
    num_embeddings = table.shape[0]
    if vocab_shards(num_embeddings, vocab_axis) <= 1:
        # zero-fill out-of-range/negative ids so the take path agrees with
        # the one-hot path (whose one_hot rows are all-zero for OOB ids) —
        # and with the reference semantics, where an id outside every
        # rank's vocab slice is masked on all ranks and psums to zero
        # (reference: fengshen/models/megatron/mpu/layers.py:106-129)
        valid = (ids >= 0) & (ids < num_embeddings)
        out = jnp.take(table, jnp.clip(ids, 0, num_embeddings - 1), axis=0)
        return out * valid[..., None].astype(table.dtype)
    from fengshen_tpu.parallel.partition import with_sharding_constraint

    one_hot = jax.nn.one_hot(ids, num_embeddings, dtype=table.dtype)
    if ids.ndim == 2:
        one_hot = with_sharding_constraint(
            one_hot, P(BATCH_AXES, SEQUENCE_AXIS, vocab_axis))
    elif ids.ndim >= 1:
        one_hot = with_sharding_constraint(
            one_hot, P(*([None] * ids.ndim), vocab_axis))
    return jax.lax.dot_general(
        one_hot, table,
        dimension_numbers=(((one_hot.ndim - 1,), (0,)), ((), ())))


class VocabParallelEmbed(nn.Module):
    """Drop-in for ``nn.Embed`` on vocab-sharded tables.

    Same parameter name/shape ("embedding", [V, H]) and call semantics as
    ``nn.Embed``, so partition rules and checkpoint importers are unchanged;
    only the lookup differs (see module docstring).
    """

    num_embeddings: int
    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    embedding_init: Callable = default_embed_init
    vocab_axis: str = TENSOR_AXIS

    def setup(self):
        # setup-defined (not compact) so tied LM heads can read
        # `module.embedding` exactly as they do with nn.Embed
        self.embedding = self.param("embedding", self.embedding_init,
                                    (self.num_embeddings, self.features),
                                    self.param_dtype)

    def __call__(self, inputs: jax.Array) -> jax.Array:
        return embed_lookup(jnp.asarray(self.embedding, self.dtype), inputs,
                            self.vocab_axis)

    def attend(self, query: jax.Array) -> jax.Array:
        """Tied-head logits: query @ embedding.T (nn.Embed API parity)."""
        return query @ jnp.asarray(self.embedding, self.dtype).T
