"""ALiBi attention bias.

Reference: fengshen/models/megatron/layers/positional_embeddings.py:90-173
(`AliBi` with cached bias and TP-rank-aware slope slicing). Under GSPMD the
head dim is sharded by the compiler, so no explicit rank slicing is needed —
we just build the full [H, Sq, Sk] bias and let XLA partition it.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def alibi_slopes(num_heads: int) -> jax.Array:
    """Per-head slopes (reference: positional_embeddings.py:100-123 —
    power-of-two geometric slopes with interpolation for non-pow2 counts)."""

    def pow2_slopes(n: int) -> list[float]:
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        slopes = pow2_slopes(num_heads)
    else:
        closest = 2 ** math.floor(math.log2(num_heads))
        slopes = pow2_slopes(closest)
        extra = pow2_slopes(2 * closest)[0::2][: num_heads - closest]
        slopes = slopes + extra
    return jnp.asarray(slopes, dtype=jnp.float32)


def alibi_bias(num_heads: int, q_len: int, k_len: int,
               dtype=jnp.float32) -> jax.Array:
    """[H, Sq, Sk] additive bias: slope * -(relative distance)
    (reference: positional_embeddings.py:125-173)."""
    slopes = alibi_slopes(num_heads)
    q_pos = jnp.arange(k_len - q_len, k_len)[:, None]
    k_pos = jnp.arange(k_len)[None, :]
    distance = -jnp.abs(q_pos - k_pos).astype(jnp.float32)
    return (slopes[:, None, None] * distance[None]).astype(dtype)
