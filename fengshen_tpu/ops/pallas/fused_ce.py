"""Fused LM-head + cross-entropy Mosaic kernel (logits never live).

The chunked XLA lowering (`ops/fused_ce.fused_lm_head_ce`) already
bounds peak logits memory to one sequence chunk; this kernel takes the
same idea to its limit: the ``[T, V]`` logits never exist outside a
``[block_t, block_v]`` VMEM tile. The forward streams vocab tiles per
token tile, keeping online-logsumexp / gold-logit / running-argmax
stats in scratch; the backward recomputes each tile's scores (flash
style — nothing but per-token ``lse`` is saved) and accumulates
``d·Kᵀ`` / ``xᵀ·d`` without materializing ``d`` beyond one tile.

Dispatch (fengshen_tpu/ops/pallas/__init__.py): ``fused_ce_loss``
routes to :func:`pallas_fused_ce` on a Mosaic-capable backend with
tile-aligned shapes, else :func:`xla_fused_ce` — the stock chunked
scan, so CPU tier-1 pins the loss path bit-for-bit. The vocab-SHARDED
variant (tensor-parallel LM head) is
``parallel.cross_entropy.fused_vocab_parallel_ce``, which runs this
seam per shard with the mpu-style collectives outside.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fengshen_tpu.ops.fused_ce import fused_lm_head_ce

_NEG_INF = -1e30


def fused_ce_loss(hidden: jax.Array, kernel: jax.Array,
                  labels: jax.Array, num_chunks: int = 8,
                  ignore_index: int = -100,
                  impl: Optional[str] = None,
                  interpret: bool = False):
    """Dispatch seam for the fused LM-head CE: hidden ``[B, S, H]`` @
    kernel ``[H, V]`` scored against labels ``[B, S]`` →
    (mean_loss, n_valid, n_correct), full logits never materialized.
    ``impl=None`` asks the capability probe + shape eligibility."""
    if impl is None:
        from fengshen_tpu.ops.pallas import probe
        use_pallas = probe().pallas_tpu and pallas_ce_eligible(hidden,
                                                              kernel)
        impl = "pallas" if use_pallas else "xla"
    if impl == "pallas":
        return pallas_fused_ce(hidden, kernel, labels,
                               num_chunks=num_chunks,
                               ignore_index=ignore_index,
                               interpret=interpret)
    return xla_fused_ce(hidden, kernel, labels, num_chunks=num_chunks,
                        ignore_index=ignore_index)


def pallas_ce_eligible(hidden, kernel) -> bool:
    """Tile alignment for the Mosaic path: hidden dim and vocab must
    split into 128-multiple lanes."""
    return kernel.shape[0] % 128 == 0 and kernel.shape[1] % 128 == 0


def xla_fused_ce(hidden, kernel, labels, num_chunks: int = 8,
                 ignore_index: int = -100):
    """The stock lowering: the seq-chunked ``lax.scan`` +
    ``jax.checkpoint`` fused head (ops/fused_ce.py), unchanged — the
    trainer's pre-seam loss path, so dispatch through here is
    bit-identical on CPU tier-1."""
    return fused_lm_head_ce(hidden, kernel, labels,
                            num_chunks=num_chunks,
                            ignore_index=ignore_index)


# -- forward kernel -----------------------------------------------------

def _ce_fwd_kernel(x_ref, k_ref, lab_ref, lse_ref, gold_ref, amax_ref,
                   m_ref, l_ref, g_ref, av_ref, ai_ref, *,
                   n_vblocks, block_v):
    """Grid (token tiles, vocab tiles), vocab innermost sequential.
    Scratch carries per-token online stats across vocab tiles: running
    max/sum (logsumexp), the gold logit (exactly one tile contributes),
    and the running argmax (value + global index, first-max tie rule
    like ``jnp.argmax``)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        g_ref[...] = jnp.zeros_like(g_ref)
        av_ref[...] = jnp.full_like(av_ref, _NEG_INF)
        ai_ref[...] = jnp.zeros_like(ai_ref)

    x = x_ref[...].astype(jnp.float32)               # [bt, H]
    kb = k_ref[...].astype(jnp.float32)              # [H, bv]
    scores = jax.lax.dot_general(
        x, kb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bt, bv]
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    lab = lab_ref[0][:, None]                        # [bt, 1]

    m_prev = m_ref[...]                              # [bt, 1]
    m_new = jnp.maximum(m_prev, scores.max(-1, keepdims=True))
    l_ref[...] = (l_ref[...] * jnp.exp(m_prev - m_new) +
                  jnp.exp(scores - m_new).sum(-1, keepdims=True))
    m_ref[...] = m_new
    g_ref[...] += jnp.where(cols == lab, scores,
                            0.0).sum(-1, keepdims=True)
    tile_val = scores.max(-1, keepdims=True)
    tile_arg = (jnp.argmax(scores, axis=-1)[:, None].astype(jnp.int32) +
                j * block_v)
    better = tile_val > av_ref[...]
    ai_ref[...] = jnp.where(better, tile_arg, ai_ref[...])
    av_ref[...] = jnp.maximum(av_ref[...], tile_val)

    @pl.when(j == n_vblocks - 1)
    def _finalize():
        lse = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))
        lse_ref[0, :] = lse[:, 0]
        gold_ref[0, :] = g_ref[...][:, 0]
        amax_ref[0, :] = ai_ref[...][:, 0]


# -- backward kernels (flash-style recompute; only lse is saved) --------

def _ce_bwd_dx_kernel(x_ref, k_ref, lab_ref, lse_ref, c_lse_ref,
                      c_gold_ref, dx_ref, acc_ref, *,
                      n_vblocks, block_v):
    """dlogits = c_lse·softmax + c_gold·onehot, one vocab tile at a
    time; dx accumulates ``dlogits @ Kᵀ`` across the tiles."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    kb = k_ref[...].astype(jnp.float32)
    scores = jax.lax.dot_general(
        x, kb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(scores - lse_ref[0][:, None])
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    onehot = (cols == lab_ref[0][:, None]).astype(jnp.float32)
    d = (p * c_lse_ref[0][:, None] +
         onehot * c_gold_ref[0][:, None])            # [bt, bv]
    acc_ref[...] += jax.lax.dot_general(
        d, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bt, H]

    @pl.when(j == n_vblocks - 1)
    def _finalize():
        dx_ref[...] = acc_ref[...].astype(dx_ref.dtype)


def _ce_bwd_dk_kernel(x_ref, k_ref, lab_ref, lse_ref, c_lse_ref,
                      c_gold_ref, dk_ref, acc_ref, *,
                      n_tblocks, block_v):
    """Same tile recompute, token tiles innermost: dK accumulates
    ``xᵀ @ dlogits`` for one vocab stripe across all token tiles."""
    i = pl.program_id(0)
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    kb = k_ref[...].astype(jnp.float32)
    scores = jax.lax.dot_general(
        x, kb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(scores - lse_ref[0][:, None])
    cols = i * block_v + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    onehot = (cols == lab_ref[0][:, None]).astype(jnp.float32)
    d = (p * c_lse_ref[0][:, None] +
         onehot * c_gold_ref[0][:, None])
    acc_ref[...] += jax.lax.dot_general(
        x, d, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [H, bv]

    @pl.when(t == n_tblocks - 1)
    def _finalize():
        dk_ref[...] = acc_ref[...].astype(dk_ref.dtype)


def _pick_block(dim: int, candidates=(512, 256, 128)) -> int:
    for c in candidates:
        if dim % c == 0:
            return c
    return dim


def _token_stats_impl(x, kernel, labels, block_t, block_v, interpret):
    n_t, hid = x.shape
    vocab = kernel.shape[1]
    n_tblocks, n_vblocks = n_t // block_t, vocab // block_v
    lab2 = labels.astype(jnp.int32)[None]            # [1, T]
    kernel_fn = functools.partial(_ce_fwd_kernel, n_vblocks=n_vblocks,
                                  block_v=block_v)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(n_tblocks, n_vblocks),
        in_specs=[
            pl.BlockSpec((block_t, hid), lambda i, j: (i, 0)),
            pl.BlockSpec((hid, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.int32),
        ],
    )
    lse, gold, amax = pl.pallas_call(
        kernel_fn, grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, n_t), jnp.float32),
            jax.ShapeDtypeStruct((1, n_t), jnp.float32),
            jax.ShapeDtypeStruct((1, n_t), jnp.int32),
        ],
        interpret=interpret,
    )(x, kernel, lab2)
    return lse[0], gold[0], amax[0]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _token_stats(x, kernel, labels, block_t, block_v, interpret):
    """x [T, H], kernel [H, V], labels [T] →
    (lse [T], gold logit [T], argmax id [T] int32)."""
    return _token_stats_impl(x, kernel, labels, block_t, block_v,
                             interpret)


def _token_stats_fwd(x, kernel, labels, block_t, block_v, interpret):
    lse, gold, amax = _token_stats_impl(x, kernel, labels, block_t,
                                        block_v, interpret)
    return (lse, gold, amax), (x, kernel, labels, lse)


def _token_stats_bwd(block_t, block_v, interpret, res, cts):
    x, kernel, labels, lse = res
    c_lse, c_gold, _ = cts                           # amax: int, no grad
    n_t, hid = x.shape
    vocab = kernel.shape[1]
    n_tblocks, n_vblocks = n_t // block_t, vocab // block_v
    lab2 = labels.astype(jnp.int32)[None]
    lse2 = lse[None]
    c_lse2 = c_lse.astype(jnp.float32)[None]
    c_gold2 = c_gold.astype(jnp.float32)[None]

    row_specs = [
        pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
        pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
        pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
        pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
    ]
    dx_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(n_tblocks, n_vblocks),
        in_specs=[
            pl.BlockSpec((block_t, hid), lambda i, j: (i, 0)),
            pl.BlockSpec((hid, block_v), lambda i, j: (0, j)),
            *row_specs,
        ],
        out_specs=pl.BlockSpec((block_t, hid), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((block_t, hid), jnp.float32)],
    )
    dx = pl.pallas_call(
        functools.partial(_ce_bwd_dx_kernel, n_vblocks=n_vblocks,
                          block_v=block_v),
        grid_spec=dx_spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, kernel, lab2, lse2, c_lse2, c_gold2)

    row_specs_t = [
        pl.BlockSpec((1, block_t), lambda i, t: (0, t)),
        pl.BlockSpec((1, block_t), lambda i, t: (0, t)),
        pl.BlockSpec((1, block_t), lambda i, t: (0, t)),
        pl.BlockSpec((1, block_t), lambda i, t: (0, t)),
    ]
    dk_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(n_vblocks, n_tblocks),
        in_specs=[
            pl.BlockSpec((block_t, hid), lambda i, t: (t, 0)),
            pl.BlockSpec((hid, block_v), lambda i, t: (0, i)),
            *row_specs_t,
        ],
        out_specs=pl.BlockSpec((hid, block_v), lambda i, t: (0, i)),
        scratch_shapes=[pltpu.VMEM((hid, block_v), jnp.float32)],
    )
    dk = pl.pallas_call(
        functools.partial(_ce_bwd_dk_kernel, n_tblocks=n_tblocks,
                          block_v=block_v),
        grid_spec=dk_spec,
        out_shape=jax.ShapeDtypeStruct(kernel.shape, kernel.dtype),
        interpret=interpret,
    )(x, kernel, lab2, lse2, c_lse2, c_gold2)
    return dx, dk, None


_token_stats.defvjp(_token_stats_fwd, _token_stats_bwd)


def pallas_fused_ce(hidden: jax.Array, kernel: jax.Array,
                    labels: jax.Array, num_chunks: int = 8,
                    ignore_index: int = -100,
                    block_t: int = 256, block_v: Optional[int] = None,
                    interpret: bool = False):
    """Mosaic fused-head CE. Same contract as
    ``ops.fused_ce.fused_lm_head_ce`` (``num_chunks`` is accepted for
    signature parity and ignored — the kernel's tiling replaces it):
    returns (mean_loss, n_valid, n_correct), differentiable w.r.t.
    hidden and kernel."""
    del num_chunks
    bsz, seq, hid = hidden.shape
    n_t = bsz * seq
    x = hidden.reshape(n_t, hid)
    lab = labels.reshape(n_t)
    block_t = _pick_block(n_t, (block_t, 256, 128, 8))
    if n_t % block_t:
        pad = block_t - n_t % block_t
        x = jnp.pad(x, ((0, pad), (0, 0)))
        lab = jnp.pad(lab, (0, pad), constant_values=ignore_index)
    if block_v is None:
        block_v = _pick_block(kernel.shape[1])
    lse, gold, amax = _token_stats(x, kernel, lab, block_t, block_v,
                                   interpret)
    valid = lab != ignore_index
    token_loss = (lse - gold) * valid
    n_valid = valid.sum()
    n_correct = ((amax == lab) & valid).sum()
    return (token_loss.sum() / jnp.maximum(n_valid, 1),
            n_valid, n_correct)
