"""Kernel-layer microbench: the Pallas dispatch seam A/B'd against the
stock XLA lowerings, plus the 32k long-context trainer config.

    make kernel-bench
    KERNEL_BENCH_MODE=decode python -m fengshen_tpu.ops.pallas.bench

Emits one BENCH-schema JSON line per rung ({"metric", "value", "unit",
"vs_baseline", ...}) through the unified jsonl sink:

- ``kernel_paged_decode_tokens_per_sec`` — the decode-attention seam
  (ops/pallas/decode_attention.py) reading a paged int8 KV pool through
  the block table, vs the pre-seam path that first gathers the pool
  into a per-lane ``[B, virt_len, ...]`` buffer with ``jnp.take`` and
  dequantizes it before attending. ``vs_baseline`` = seam / gather.
- ``kernel_fused_ce_steps_per_sec`` — the fused LM-head CE seam
  (ops/pallas/fused_ce.py) grad step vs the naive materialized
  ``[B, S, V]`` logits + log_softmax CE. ``vs_baseline`` =
  fused / materialized.
- ``long_context_tokens_per_sec`` — the ``configs/long_context_32k.json``
  trainer config (ring/ulysses context parallelism, docs/kernels.md)
  driven through the real Trainer on a sequence-sharded mesh.
  ``vs_baseline`` = 1.0 (no published long-context baseline).

Every row carries ``kernel`` — the dispatch decision (``pallas`` on a
real TPU, ``xla`` on the CPU fallback) — which benchdiff folds into the
row identity: a Mosaic round and a stock-lowering round measure
different programs and must diff as incomparable, never regression.

Env knobs (KERNEL_BENCH_*): MODE (decode | fused_ce | long_context |
all), BATCH, ITERS, STEPS, SEQ, HIDDEN, INTER, LAYERS, HEADS, KV,
VOCAB, SP (sequence-parallel degree), CONFIG (long-context config
path). The Makefile target runs a CPU-shrunk smoke of all three rungs;
hardware rounds drop the overrides and get the full 32k shape.
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _env(name: str, default: int) -> int:
    return int(os.environ.get(f"KERNEL_BENCH_{name}", default))


def _emit(row: dict) -> None:
    from fengshen_tpu.observability import JsonlSink
    if os.environ.get("BENCH_DEGRADED", "0") == "1":
        row["degraded"] = True
    JsonlSink(stream=sys.stdout, only_process_zero=False)(row)


def _time_calls(fn, args, iters: int) -> float:
    """Seconds per call of an already-jitted fn (one warmup dispatch
    first so compile never lands in the window)."""
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_paged_decode() -> dict:
    """Paged int8 decode attention: the dispatch seam's block-table
    read vs the pre-seam gather-then-attend path."""
    from fengshen_tpu.ops.attention import dot_product_attention
    from fengshen_tpu.ops.pallas import kernel_choice
    from fengshen_tpu.ops.pallas.decode_attention import decode_attention
    from fengshen_tpu.ops.int8_matmul import dequantize_kv

    batch = _env("BATCH", 8)
    iters = _env("ITERS", 30)
    n_heads, kv_heads, head_dim = 8, 4, 128
    block_size, blocks_per_lane = 128, 4
    virt_len = block_size * blocks_per_lane
    n_blocks = batch * blocks_per_lane
    ctx = virt_len - block_size // 2  # a partially-filled last block

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(batch, 1, n_heads, head_dim), jnp.float32)
    k_pool = jnp.asarray(
        rng.randint(-127, 128, (n_blocks, block_size, kv_heads, head_dim)),
        jnp.int8)
    v_pool = jnp.asarray(
        rng.randint(-127, 128, (n_blocks, block_size, kv_heads, head_dim)),
        jnp.int8)
    k_scale = jnp.asarray(
        rng.rand(n_blocks, block_size, kv_heads) * 0.05, jnp.float32)
    v_scale = jnp.asarray(
        rng.rand(n_blocks, block_size, kv_heads) * 0.05, jnp.float32)
    table = jnp.asarray(
        rng.permutation(n_blocks).reshape(batch, blocks_per_lane),
        jnp.int32)
    valid = jnp.asarray(np.broadcast_to(
        np.arange(virt_len) < ctx, (batch, 1, virt_len)).copy())

    @jax.jit
    def seam(q, k_pool, v_pool, k_scale, v_scale, table, valid):
        return decode_attention(q, k_pool, v_pool, valid,
                                k_scale=k_scale, v_scale=v_scale,
                                block_table=table,
                                dequant_dtype=jnp.float32)

    @jax.jit
    def gather(q, k_pool, v_pool, k_scale, v_scale, table, valid):
        # the pre-seam lowering: materialize the lane-contiguous KV with
        # jnp.take, dequantize the copy, then attend
        flat = (table * block_size)[:, :, None] + jnp.arange(block_size)
        idx = flat.reshape(batch, virt_len)
        k = jnp.take(k_pool.reshape(n_blocks * block_size, kv_heads,
                                    head_dim), idx, axis=0)
        v = jnp.take(v_pool.reshape(n_blocks * block_size, kv_heads,
                                    head_dim), idx, axis=0)
        ks = jnp.take(k_scale.reshape(n_blocks * block_size, kv_heads),
                      idx, axis=0)
        vs = jnp.take(v_scale.reshape(n_blocks * block_size, kv_heads),
                      idx, axis=0)
        k = dequantize_kv(k, ks, jnp.float32)
        v = dequantize_kv(v, vs, jnp.float32)
        rep = n_heads // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        return dot_product_attention(q, k, v, mask=valid[:, None])

    args = (q, k_pool, v_pool, k_scale, v_scale, table, valid)
    seam_s = _time_calls(seam, args, iters)
    gather_s = _time_calls(gather, args, iters)
    return {
        "metric": "kernel_paged_decode_tokens_per_sec",
        "value": round(batch / seam_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(gather_s / seam_s, 4),
        "kernel": kernel_choice("decode_attention"),
        "backend": jax.default_backend(),
        "batch": batch, "virt_len": virt_len, "quant": "int8",
    }


def bench_fused_ce() -> dict:
    """Fused LM-head CE grad step vs materialized-logits CE."""
    from fengshen_tpu.ops.pallas import kernel_choice
    from fengshen_tpu.ops.pallas.fused_ce import fused_ce_loss

    batch = _env("BATCH", 4)
    seq = _env("SEQ", 512)
    hidden_dim = _env("HIDDEN", 256)
    vocab = _env("VOCAB", 2048)
    iters = _env("ITERS", 10)

    rng = np.random.RandomState(1)
    hidden = jnp.asarray(
        rng.randn(batch, seq, hidden_dim) * 0.05, jnp.float32)
    head = jnp.asarray(
        rng.randn(hidden_dim, vocab) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.randint(0, vocab, (batch, seq)), jnp.int32)

    @jax.jit
    @jax.grad
    def fused(head, hidden, labels):
        return fused_ce_loss(hidden, head, labels)[0]

    @jax.jit
    @jax.grad
    def materialized(head, hidden, labels):
        logits = hidden @ head  # the full [B, S, V] tensor
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        gold = jnp.take_along_axis(logp, labels[..., None], axis=-1)
        return -gold.mean()

    args = (head, hidden, labels)
    fused_s = _time_calls(fused, args, iters)
    naive_s = _time_calls(materialized, args, iters)
    return {
        "metric": "kernel_fused_ce_steps_per_sec",
        "value": round(1.0 / fused_s, 2),
        "unit": "steps/s",
        "vs_baseline": round(naive_s / fused_s, 4),
        "kernel": kernel_choice("fused_ce"),
        "backend": jax.default_backend(),
        "tokens": batch * seq, "vocab": vocab,
    }


def bench_long_context() -> dict:
    """The 32k long-context trainer config through the real Trainer:
    ring/ulysses context parallelism over the mesh 'sequence' axis.
    KERNEL_BENCH_{SEQ,HIDDEN,...} shrink the shape for CPU smokes —
    same config file, same attention path, smaller tile."""
    import argparse
    import dataclasses
    import json
    import tempfile

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.ops.pallas import kernel_choice
    from fengshen_tpu.parallel import set_mesh
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule

    cfg_path = os.environ.get(
        "KERNEL_BENCH_CONFIG",
        os.path.join(os.path.dirname(__file__), "..", "..", "..",
                     "configs", "long_context_32k.json"))
    config = LlamaConfig.from_pretrained(cfg_path)
    # CPU smoke shrinks WIDTH, never the attention path: the rung's
    # point is the 32k-class sequence through ring/ulysses
    overrides = {
        "max_position_embeddings": _env(
            "SEQ", config.max_position_embeddings),
        "hidden_size": _env("HIDDEN", config.hidden_size),
        "intermediate_size": _env("INTER", config.intermediate_size),
        "num_hidden_layers": _env("LAYERS", config.num_hidden_layers),
        "num_attention_heads": _env("HEADS", config.num_attention_heads),
        "num_key_value_heads": _env("KV", config.num_key_value_heads),
        "vocab_size": _env("VOCAB", config.vocab_size),
        "fused_ce_chunks": _env("FUSED_CE", config.fused_ce_chunks),
    }
    if os.environ.get("KERNEL_BENCH_DTYPE"):
        overrides["dtype"] = os.environ["KERNEL_BENCH_DTYPE"]
        overrides["param_dtype"] = os.environ["KERNEL_BENCH_DTYPE"]
    config = dataclasses.replace(config, **overrides)
    if config.hidden_size % config.num_attention_heads:
        raise ValueError("KERNEL_BENCH_HEADS must divide "
                         "KERNEL_BENCH_HIDDEN")
    config.multiple_of = min(config.multiple_of, config.hidden_size)

    seq = config.max_position_embeddings
    batch = _env("BATCH", 1)
    steps = _env("STEPS", 2)
    sp = _env("SP", min(len(jax.devices()), 8))

    root = tempfile.mkdtemp(prefix="fstpu_kernel_bench_")
    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", str(steps), "--train_batchsize", str(batch),
        "--data_parallel_size", "1", "--fsdp_parallel_size", "1",
        "--sequence_parallel_size", str(sp),
        "--tensor_model_parallel_size", "1",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", root])

    rng = np.random.RandomState(2)
    rows = [{"input_ids":
             rng.randint(0, config.vocab_size - 1, seq).tolist()}
            for _ in range(batch * (steps + 1))]

    class DS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    trainer = Trainer(args)
    module = CausalLMModule(args, LlamaForCausalLM(config), config)
    dm = UniversalDataModule(args=args, datasets={"train": DS()})
    t0 = time.perf_counter()
    state = trainer.fit(module, dm)
    jax.block_until_ready(state.params)
    elapsed = time.perf_counter() - t0
    set_mesh(None)
    # steady-state step time from the trainer's own windowed metric
    # when available; the wall clock (compile included) is the honest
    # fallback for very short smokes
    tps_list = []
    try:
        with open(os.path.join(root, "metrics.jsonl")) as f:
            tps_list = [json.loads(line).get("tokens_per_sec")
                        for line in f]
        tps_list = [t for t in tps_list[1:] if t]
    except OSError:
        pass
    tps = float(np.mean(tps_list)) if tps_list else \
        int(state.step) * batch * seq / elapsed
    return {
        "metric": "long_context_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
        "kernel": kernel_choice("flash_attention"),
        "backend": jax.default_backend(),
        "seq": seq, "attention_impl": config.attention_impl,
        "sequence_parallel": sp,
    }


_RUNGS = {
    "decode": bench_paged_decode,
    "fused_ce": bench_fused_ce,
    "long_context": bench_long_context,
}


def main() -> int:
    mode = os.environ.get("KERNEL_BENCH_MODE", "all")
    names = list(_RUNGS) if mode == "all" else [mode]
    unknown = [n for n in names if n not in _RUNGS]
    if unknown:
        print(f"kernel-bench: unknown KERNEL_BENCH_MODE {mode!r} "
              f"(expected {'|'.join(_RUNGS)}|all)", file=sys.stderr)
        return 2
    for name in names:
        _emit(_RUNGS[name]())
    return 0


if __name__ == "__main__":
    sys.exit(main())
