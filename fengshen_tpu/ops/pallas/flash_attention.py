"""Pallas TPU flash-attention kernels (forward + fused backward).

The TPU-native replacement for the reference's flash-attention CUDA binding
(reference: fengshen/models/megatron/layers/flash_attention.py wraps
flash_attn_cuda.fwd/bwd). Three kernels:

- forward: online softmax with k/v streamed block-by-block through VMEM via
  the grid (memory per program is O(blk_q + blk_k), never O(Sk)); running
  statistics live in VMEM scratch across the innermost (k-block) grid
  dimension — TPU grids execute sequentially, so scratch persists between k
  steps of the same q block. Emits the per-row logsumexp as a residual.
- backward dkv: for each k/v block, stream q/dO blocks and accumulate
  dv += P^T·dO and dk += dS^T·q in VMEM scratch (the fused analog of
  flash_attn_cuda.bwd's column-block loop).
- backward dq: for each q block, stream k/v blocks and accumulate dq += dS·k.

Padded / packed batches are expressed as integer segment ids (q and kv):
tokens attend only within equal segment ids, so an SFT attention_mask maps
to seg = mask (pads form segment 0) and packed examples map to per-example
ids — this is what lets the flagship padded-SFT path stay on the fused
kernel instead of falling back to dense O(S²) (VERDICT round 1, weak #3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _mask_scores(scores, causal, q_start, k_start, blk_q, blk_k,
                 seg_q, seg_k):
    """Apply causal and/or segment-id masking to a [blk_q, blk_k] tile."""
    allowed = None
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1)
        allowed = k_pos <= q_pos
    if seg_q is not None:
        same = seg_q.reshape(blk_q, 1) == seg_k.reshape(1, blk_k)
        allowed = same if allowed is None else (allowed & same)
    if allowed is None:
        return scores
    return jnp.where(allowed, scores, _NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, seg_q_ref, seg_k_ref,
                o_ref, lse_ref, acc_ref, max_ref, sum_ref,
                *, blk_k: int, causal: bool, scale: float,
                n_kblocks: int, q_offset: int, has_segments: bool):
    # q_ref/o_ref: [1, 1, blk_q, D]; k_ref/v_ref: [1, 1, blk_k, D]
    # seg refs: [1, 1, blk] and lse_ref: [1, 1, 1, blk_q] — the singleton
    # dims keep each block's last two dims Mosaic-tileable
    # q_offset = k_len - q_len: queries right-aligned with keys (the KV-cache
    # decode convention, same as ops.flash_attention.blockwise)
    blk_q, head_dim = q_ref.shape[2], q_ref.shape[3]
    q_idx = pl.program_id(2)
    kb = pl.program_id(3)
    q_start = q_offset + q_idx * blk_q
    k_start = kb * blk_k

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        max_ref[:] = jnp.full_like(max_ref, _NEG_INF)
        sum_ref[:] = jnp.zeros_like(sum_ref)

    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k_blk = k_ref[0, 0].astype(jnp.float32)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [blk_q, blk_k]
        seg_q = seg_q_ref[0, 0] if has_segments else None
        seg_k = seg_k_ref[0, 0] if has_segments else None
        scores = _mask_scores(scores, causal, q_start, k_start,
                              blk_q, blk_k, seg_q, seg_k)
        row_max = max_ref[:, 0]
        blk_max = scores.max(axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[:, None])
        sum_ref[:, 0] = sum_ref[:, 0] * correction + probs.sum(axis=-1)
        max_ref[:, 0] = new_max
        acc_ref[:] = acc_ref[:] * correction[:, None] + jax.lax.dot_general(
            probs, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip blocks strictly above the causal diagonal
        pl.when(k_start <= q_start + blk_q - 1)(_step)
    else:
        _step()

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        denom = jnp.maximum(sum_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / denom[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = max_ref[:, 0] + jnp.log(denom)


def _fwd_impl(q, k, v, q_seg, kv_seg, causal, blk_q, blk_k, interpret):
    """q: [B, H, S, D]; k/v: [B, KVH, S, D] with H % KVH == 0 (GQA reads
    each KV head from HBM once per group instead of materialising the
    repeated tensor); segs: [B, S] int32 or None.
    Returns (out [B, H, Sq, D], lse [B, H, 1, Sq])."""
    batch, num_heads, q_len, head_dim = q.shape
    k_len = k.shape[2]
    rep = num_heads // k.shape[1]  # q heads per kv head (1 = MHA)
    blk_q = min(blk_q, q_len)
    blk_k = min(blk_k, k_len)
    assert q_len % blk_q == 0 and k_len % blk_k == 0
    scale = float(1.0 / (head_dim ** 0.5))
    n_kblocks = k_len // blk_k
    has_segments = q_seg is not None
    if not has_segments:  # dummy operands keep one kernel signature
        q_seg = jnp.zeros((batch, q_len), jnp.int32)
        kv_seg = jnp.zeros((batch, k_len), jnp.int32)
    # [B, 1, S]: Mosaic needs the block's last two dims (8,128)-tileable
    # or equal to the array's — the singleton middle dim satisfies that
    q_seg3, kv_seg3 = q_seg[:, None, :], kv_seg[:, None, :]

    kernel = functools.partial(
        _fwd_kernel, blk_k=blk_k, causal=causal, scale=scale,
        n_kblocks=n_kblocks, q_offset=k_len - q_len,
        has_segments=has_segments)
    out, lse = pl.pallas_call(
        kernel,
        grid=(batch, num_heads, q_len // blk_q, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, blk_k, head_dim),
                         lambda b, h, i, j: (b, h // rep, j, 0)),
            pl.BlockSpec((1, 1, blk_k, head_dim),
                         lambda b, h, i, j: (b, h // rep, j, 0)),
            pl.BlockSpec((1, 1, blk_q), lambda b, h, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, blk_k), lambda b, h, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, blk_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, blk_q),
                         lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, num_heads, 1, q_len),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, head_dim), jnp.float32),  # acc
            pltpu.VMEM((blk_q, 1), jnp.float32),         # running max
            pltpu.VMEM((blk_q, 1), jnp.float32),         # running sum
        ],
        interpret=interpret,
    )(q, k, v, q_seg3, kv_seg3)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    seg_q_ref, seg_k_ref, dk_ref, dv_ref,
                    dk_acc, dv_acc,
                    *, blk_q: int, causal: bool, scale: float,
                    n_qblocks: int, q_offset: int, has_segments: bool):
    # grid (B, H, n_k, n_q): innermost loop over q blocks, scratch holds the
    # running dk/dv for one k block (the column-block loop of flash bwd).
    blk_k = k_ref.shape[2]
    kb = pl.program_id(2)
    qi = pl.program_id(3)
    k_start = kb * blk_k
    q_start = q_offset + qi * blk_q

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k_blk = k_ref[0, 0].astype(jnp.float32)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0]      # [blk_q]
        delta = delta_ref[0, 0, 0]  # [blk_q]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        seg_q = seg_q_ref[0, 0] if has_segments else None
        seg_k = seg_k_ref[0, 0] if has_segments else None
        scores = _mask_scores(scores, causal, q_start, k_start,
                              blk_q, blk_k, seg_q, seg_k)
        p = jnp.exp(scores - lse[:, None])              # [blk_q, blk_k]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # P^T · dO
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # dO · V^T
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # dS^T · Q

    if causal:
        # a q block contributes only if it reaches the diagonal of this
        # k block: q_end >= k_start
        pl.when(q_start + blk_q - 1 >= k_start)(_step)
    else:
        _step()

    @pl.when(qi == n_qblocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   seg_q_ref, seg_k_ref, dq_ref, dq_acc,
                   *, blk_k: int, causal: bool, scale: float,
                   n_kblocks: int, q_offset: int, has_segments: bool):
    # grid (B, H, n_q, n_k): innermost loop over k blocks, scratch holds the
    # running dq for one q block.
    blk_q = q_ref.shape[2]
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    q_start = q_offset + qi * blk_q
    k_start = kb * blk_k

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k_blk = k_ref[0, 0].astype(jnp.float32)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0]
        delta = delta_ref[0, 0, 0]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        seg_q = seg_q_ref[0, 0] if has_segments else None
        seg_k = seg_k_ref[0, 0] if has_segments else None
        scores = _mask_scores(scores, causal, q_start, k_start,
                              blk_q, blk_k, seg_q, seg_k)
        p = jnp.exp(scores - lse[:, None])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # dS · K

    if causal:
        pl.when(k_start <= q_start + blk_q - 1)(_step)
    else:
        _step()

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_impl(q, k, v, q_seg, kv_seg, out, lse, do,
              causal, blk_q, blk_k, interpret):
    """q/out/do: [B, H, S, D]; k/v: [B, KVH, S, D]; returns (dq, dk, dv)
    with dk/dv at the KV head count. GQA backward runs the MHA kernels on
    transiently repeated K/V and group-sums dk/dv — only the forward
    avoids the repeat (the backward already reads full-size dO)."""
    batch, num_heads, q_len, head_dim = q.shape
    kv_heads = k.shape[1]
    if kv_heads != num_heads:
        rep = num_heads // kv_heads
        dq, dk_full, dv_full = _bwd_impl(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            q_seg, kv_seg, out, lse, do, causal, blk_q, blk_k, interpret)
        k_len = k.shape[2]
        dk = dk_full.reshape(batch, kv_heads, rep, k_len,
                             head_dim).sum(2).astype(k.dtype)
        dv = dv_full.reshape(batch, kv_heads, rep, k_len,
                             head_dim).sum(2).astype(v.dtype)
        return dq, dk, dv
    k_len = k.shape[2]
    blk_q = min(blk_q, q_len)
    blk_k = min(blk_k, k_len)
    scale = float(1.0 / (head_dim ** 0.5))
    n_qblocks, n_kblocks = q_len // blk_q, k_len // blk_k
    has_segments = q_seg is not None
    if not has_segments:
        q_seg = jnp.zeros((batch, q_len), jnp.int32)
        kv_seg = jnp.zeros((batch, k_len), jnp.int32)
    q_seg3, kv_seg3 = q_seg[:, None, :], kv_seg[:, None, :]

    # delta_i = sum_d dO_i·O_i (rowwise); cheap, XLA fuses it.
    # lse arrives as [B, H, 1, S]; delta matches that layout
    delta = (do.astype(jnp.float32) *
             out.astype(jnp.float32)).sum(-1)[:, :, None, :]

    qspec = pl.BlockSpec((1, 1, blk_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0))
    rowspec = pl.BlockSpec((1, 1, 1, blk_q),
                           lambda b, h, i, j: (b, h, 0, i))
    segq_spec = pl.BlockSpec((1, 1, blk_q), lambda b, h, i, j: (b, 0, i))

    # dkv: grid over k blocks, stream q blocks innermost
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, blk_q=blk_q, causal=causal, scale=scale,
        n_qblocks=n_qblocks, q_offset=k_len - q_len,
        has_segments=has_segments)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(batch, num_heads, n_kblocks, n_qblocks),
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, head_dim),
                         lambda b, h, i, j: (b, h, j, 0)),   # q by inner j
            pl.BlockSpec((1, 1, blk_k, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),   # k by outer i
            pl.BlockSpec((1, 1, blk_k, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),   # v by outer i
            pl.BlockSpec((1, 1, blk_q, head_dim),
                         lambda b, h, i, j: (b, h, j, 0)),   # do by inner j
            pl.BlockSpec((1, 1, 1, blk_q),
                         lambda b, h, i, j: (b, h, 0, j)),
            pl.BlockSpec((1, 1, 1, blk_q),
                         lambda b, h, i, j: (b, h, 0, j)),
            pl.BlockSpec((1, 1, blk_q), lambda b, h, i, j: (b, 0, j)),
            pl.BlockSpec((1, 1, blk_k), lambda b, h, i, j: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, blk_k, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, blk_k, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, head_dim), jnp.float32),
            pltpu.VMEM((blk_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta, q_seg3, kv_seg3)

    # dq: grid over q blocks, stream k blocks innermost
    dq_kernel = functools.partial(
        _bwd_dq_kernel, blk_k=blk_k, causal=causal, scale=scale,
        n_kblocks=n_kblocks, q_offset=k_len - q_len,
        has_segments=has_segments)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(batch, num_heads, n_qblocks, n_kblocks),
        in_specs=[qspec,
                  pl.BlockSpec((1, 1, blk_k, head_dim),
                               lambda b, h, i, j: (b, h, j, 0)),
                  pl.BlockSpec((1, 1, blk_k, head_dim),
                               lambda b, h, i, j: (b, h, j, 0)),
                  qspec, rowspec, rowspec, segq_spec,
                  pl.BlockSpec((1, 1, blk_k), lambda b, h, i, j: (b, 0, j))],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta, q_seg3, kv_seg3)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API ([B, S, H, D] layout, custom_vjp)
# ---------------------------------------------------------------------------

def _to_bhsd(x):
    return x.transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def pallas_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           q_segment_ids: jax.Array | None = None,
                           kv_segment_ids: jax.Array | None = None,
                           causal: bool = False,
                           blk_q: int = 256, blk_k: int = 256,
                           interpret: bool = False) -> jax.Array:
    """q: [B, Sq, H, D], k/v: [B, Sk, KVH, D] → [B, Sq, H, D].

    GQA: KVH may be smaller than H as long as H % KVH == 0 — each group of
    H // KVH query heads reads the same k/v head inside the kernel (no HBM
    repeat); the backward computes per-query-head dk/dv and group-sums.

    segment ids: int32 [B, S]; tokens attend only within equal ids (pads are
    segment 0 when derived from an attention_mask). Requires Sq % blk_q == 0,
    Sk % blk_k == 0 (the `_pallas_eligible` dispatch in ops.flash_attention
    guarantees tile-aligned shapes, in the spirit of the reference's
    fused-kernel availability check, reference:
    fengshen/models/megatron/layers/fused_softmax.py:148-168).
    """
    out, _ = _fwd_impl(_to_bhsd(q), _to_bhsd(k), _to_bhsd(v),
                       q_segment_ids, kv_segment_ids,
                       causal, blk_q, blk_k, interpret)
    return _to_bhsd(out)


def _flash_vjp_fwd(q, k, v, q_seg, kv_seg, causal, blk_q, blk_k, interpret):
    qt, kt, vt = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    out, lse = _fwd_impl(qt, kt, vt, q_seg, kv_seg,
                         causal, blk_q, blk_k, interpret)
    return _to_bhsd(out), (qt, kt, vt, q_seg, kv_seg, out, lse)


def _flash_vjp_bwd(causal, blk_q, blk_k, interpret, res, g):
    qt, kt, vt, q_seg, kv_seg, out, lse = res
    dq, dk, dv = _bwd_impl(qt, kt, vt, q_seg, kv_seg, out, lse,
                           _to_bhsd(g), causal, blk_q, blk_k, interpret)
    none_q = None if q_seg is None else jnp.zeros(
        q_seg.shape, jax.dtypes.float0)
    none_kv = None if kv_seg is None else jnp.zeros(
        kv_seg.shape, jax.dtypes.float0)
    return _to_bhsd(dq), _to_bhsd(dk), _to_bhsd(dv), none_q, none_kv


pallas_flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)
