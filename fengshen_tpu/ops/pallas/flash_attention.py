"""Pallas TPU flash-attention kernel.

The TPU-native replacement for the reference's flash-attention CUDA binding
(reference: fengshen/models/megatron/layers/flash_attention.py wraps
flash_attn_cuda.fwd/bwd). Forward fused kernel: online softmax with k/v
streamed block-by-block through VMEM via the grid (memory per program is
O(blk_q + blk_k), never O(Sk)), running statistics held in VMEM scratch
across the innermost (k-block) grid dimension — TPU grids execute
sequentially, so scratch persists between k steps of the same q block.

The backward pass recomputes through the differentiable XLA blockwise
implementation via `jax.custom_vjp` (flash-style recompute, trading FLOPs
for HBM traffic like `jax.checkpoint`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref,
                      acc_ref, max_ref, sum_ref,
                      *, blk_k: int, causal: bool, scale: float,
                      n_kblocks: int, q_offset: int):
    # q_ref/o_ref: [1, blk_q, D]; k_ref/v_ref: [1, blk_k, D]
    # q_offset = k_len - q_len: queries are right-aligned with keys (the
    # KV-cache decode convention, same as ops.flash_attention.blockwise)
    _, blk_q, head_dim = q_ref.shape
    q_idx = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = q_offset + q_idx * blk_q
    k_start = kb * blk_k

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        max_ref[:] = jnp.full_like(max_ref, _NEG_INF)
        sum_ref[:] = jnp.zeros_like(sum_ref)

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [blk_q, blk_k]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            scores = jnp.where(k_pos <= q_pos, scores, _NEG_INF)
        row_max = max_ref[:, 0]
        blk_max = scores.max(axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[:, None])
        sum_ref[:, 0] = sum_ref[:, 0] * correction + probs.sum(axis=-1)
        max_ref[:, 0] = new_max
        acc_ref[:] = acc_ref[:] * correction[:, None] + jax.lax.dot_general(
            probs, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip blocks strictly above the causal diagonal
        pl.when(k_start <= q_start + blk_q - 1)(_step)
    else:
        _step()

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        out = acc_ref[:] / jnp.maximum(sum_ref[:, 0], 1e-30)[:, None]
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def pallas_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           causal: bool = False,
                           blk_q: int = 256, blk_k: int = 256,
                           interpret: bool = False) -> jax.Array:
    """q: [B, Sq, H, D], k/v: [B, Sk, H, D] → [B, Sq, H, D].

    Requires Sq % blk_q == 0, Sk % blk_k == 0 (the `_pallas_eligible`
    dispatch in ops.flash_attention guarantees tile-aligned shapes, in the
    spirit of the reference's fused-kernel availability check,
    reference: fengshen/models/megatron/layers/fused_softmax.py:148-168).
    """
    return _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret)


def _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret=False):
    batch, q_len, num_heads, head_dim = q.shape
    k_len = k.shape[1]
    blk_q = min(blk_q, q_len)
    blk_k = min(blk_k, k_len)
    assert q_len % blk_q == 0 and k_len % blk_k == 0
    scale = float(1.0 / (head_dim ** 0.5))
    n_kblocks = k_len // blk_k

    # [B, S, H, D] -> [B*H, S, D]
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], x.shape[3])

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)

    kernel = functools.partial(_flash_fwd_kernel, blk_k=blk_k, causal=causal,
                               scale=scale, n_kblocks=n_kblocks,
                               q_offset=k_len - q_len)
    out = pl.pallas_call(
        kernel,
        grid=(qb.shape[0], q_len // blk_q, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, blk_q, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, head_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, head_dim), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, head_dim), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qb.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, head_dim), jnp.float32),  # acc
            pltpu.VMEM((blk_q, 1), jnp.float32),         # running max
            pltpu.VMEM((blk_q, 1), jnp.float32),         # running sum
        ],
        interpret=interpret,
    )(qb, kb, vb)

    return (out.reshape(batch, num_heads, q_len, head_dim)
               .transpose(0, 2, 1, 3))


def _flash_fwd_vjp(q, k, v, causal, blk_q, blk_k, interpret):
    out = _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, blk_q, blk_k, interpret, res, g):
    q, k, v = res
    # recompute through the XLA blockwise path, which is differentiable
    from fengshen_tpu.ops.flash_attention import blockwise_attention

    def f(q_, k_, v_):
        return blockwise_attention(q_, k_, v_, causal=causal,
                                   block_size=blk_k)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


pallas_flash_attention.defvjp(_flash_fwd_vjp, _flash_bwd)
