"""Kernel layer: registry + capability probe + dispatch seam.

Before this, the two Pallas kernels in this package (flash_attention,
block_sparse_attention) were orphans — each caller re-derived "can the
backend run Mosaic?" from ``jax.default_backend()`` inline, and the
decision never reached logs, metrics, or the AOT cache key. Now every
fused kernel registers BOTH implementations here:

- ``pallas`` — the Mosaic TPU kernel (fengshen_tpu.ops.pallas.*);
- ``xla``    — the stock lowering the kernel replaces, numerically
  identical by construction so CPU tier-1 can pin parity.

and callers route through one seam:

- :func:`probe` — cached capability probe (same shape as the offload
  ladder's ``probe_memory_capabilities``): is this backend able to run
  Mosaic kernels at all?  ``FSTPU_KERNEL_FORCE=xla|pallas`` overrides
  for benchmarking / debugging.  Cached per (backend, force) so the
  decision is made ONCE per process — dispatch inside a traced function
  reads a python bool, never a runtime branch, so it is not a
  retrace hazard.
- :func:`kernel_choice` — the per-op decision (``"pallas"`` or
  ``"xla"``), and :func:`get_kernel` to fetch the callable.
- :func:`kernel_fingerprint` — the dispatch table serialized for the
  AOT cache key (docs/aot_cache.md): a pallas-compiled executable must
  never be replayed on an xla-dispatch process and vice versa.
- :func:`log_dispatch` — THE loud line (PR 9 doctrine: degrade loudly,
  never fail) + the ``fstpu_kernel_dispatch{op,impl}`` gauge.

See docs/kernels.md for the dispatch ladder and the
writing-a-kernel checklist.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Callable, Dict, Optional

KERNEL_DISPATCH_METRIC = "fstpu_kernel_dispatch"

#: env override: "xla" benches the fallback on TPU, "pallas" forces the
#: kernels on (interpret-mode debugging); unset = probe the backend
FORCE_ENV = "FSTPU_KERNEL_FORCE"


@dataclasses.dataclass(frozen=True)
class KernelProbe:
    """One process-wide answer to "can this backend run Mosaic?"."""

    backend: str
    #: True when pl.pallas_call compiles to Mosaic on this backend —
    #: the per-op shape checks still apply on top of this
    pallas_tpu: bool
    #: the FSTPU_KERNEL_FORCE value when it decided, else None
    forced: Optional[str]
    reason: str

    def describe(self) -> dict:
        return {
            "backend": self.backend,
            "pallas_tpu": self.pallas_tpu,
            "forced": self.forced,
            "reason": self.reason,
        }


#: (backend, force-env) -> KernelProbe; keyed on the env var so a bench
#: that flips FSTPU_KERNEL_FORCE mid-process re-probes
_PROBE_CACHE: Dict[tuple, KernelProbe] = {}


def probe(refresh: bool = False) -> KernelProbe:
    """Cached capability probe. Never raises: a backend that cannot
    run Mosaic answers ``pallas_tpu=False`` with the reason, and every
    op degrades to its xla lowering (loudly — see log_dispatch)."""
    import jax

    forced = os.environ.get(FORCE_ENV, "").strip().lower() or None
    backend = jax.default_backend()
    cache_key = (backend, forced)
    if not refresh and cache_key in _PROBE_CACHE:
        return _PROBE_CACHE[cache_key]
    if forced == "xla":
        result = KernelProbe(backend, False, forced,
                             f"{FORCE_ENV}=xla pins the stock lowering")
    elif forced == "pallas":
        result = KernelProbe(backend, True, forced,
                             f"{FORCE_ENV}=pallas pins the Mosaic "
                             "kernels (off-TPU they must be run in "
                             "interpret mode or will fail at call time)")
    elif backend != "tpu":
        result = KernelProbe(backend, False, None,
                             f"backend={backend} cannot compile Mosaic "
                             "kernels; xla lowering (CPU tier-1 pins "
                             "parity against it)")
    else:
        try:
            from jax.experimental import pallas as _pl  # noqa: F401
            from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
            result = KernelProbe(backend, True, None,
                                 "tpu backend + pallas importable")
        except Exception as exc:  # noqa: BLE001 — a jax build without
            # pallas still serves/trains on the stock lowering
            result = KernelProbe(backend, False, None,
                                 f"pallas import failed: {exc!r}")
    _PROBE_CACHE[cache_key] = result
    return result


#: op -> {"pallas": fn, "xla": fn}; both impls of one op take the same
#: signature and agree numerically (the parity tests pin it)
_REGISTRY: Dict[str, Dict[str, Callable]] = {}


def register_kernel(op: str, impl: str, fn: Callable) -> Callable:
    """Register one implementation of ``op``; returns ``fn`` so it can
    be used as a decorator tail."""
    if impl not in ("pallas", "xla"):
        raise ValueError(f"impl must be 'pallas' or 'xla', got {impl!r}")
    _REGISTRY.setdefault(op, {})[impl] = fn
    return fn


def kernel_choice(op: str) -> str:
    """The dispatch decision for ``op``: ``"pallas"`` when the probe
    says the backend can run Mosaic AND the op registered a pallas
    impl, else ``"xla"``."""
    impls = _REGISTRY.get(op, {})
    if probe().pallas_tpu and "pallas" in impls:
        return "pallas"
    return "xla"


def get_kernel(op: str, impl: Optional[str] = None) -> Callable:
    """Fetch the callable for ``op`` (``impl=None`` = probed choice)."""
    impls = _REGISTRY.get(op)
    if not impls:
        raise KeyError(f"no kernel registered under {op!r}; "
                       f"known: {sorted(_REGISTRY)}")
    resolved = impl if impl is not None else kernel_choice(op)
    if resolved not in impls:
        raise KeyError(f"kernel {op!r} has no {resolved!r} impl; "
                       f"registered: {sorted(impls)}")
    return impls[resolved]


def dispatch_table() -> Dict[str, str]:
    """op -> chosen impl for every registered kernel."""
    return {op: kernel_choice(op) for op in sorted(_REGISTRY)}


def kernel_fingerprint() -> str:
    """The dispatch table as a stable string for the AOT cache key
    (docs/aot_cache.md): two processes whose kernels dispatch
    differently must never share a compiled executable."""
    table = ",".join(f"{op}:{impl}" for op, impl in
                     sorted(dispatch_table().items()))
    return f"kernels={table};backend={probe().backend}"


def log_dispatch(log: Optional[Callable[[dict], None]] = None,
                 registry=None) -> Dict[str, str]:
    """THE loud line: state every kernel's dispatch decision once at
    startup (structured sink when one exists, stderr otherwise) and set
    the ``fstpu_kernel_dispatch{op,impl}`` gauge — 1 for the chosen
    impl, 0 for the alternative, so a scraper can alert on a fleet
    silently degrading to xla. Returns the dispatch table."""
    from fengshen_tpu.observability.registry import get_registry

    info = probe()
    table = dispatch_table()
    gauge = (registry if registry is not None else get_registry()).gauge(
        KERNEL_DISPATCH_METRIC,
        "1 for each op's chosen kernel impl, 0 for the alternative",
        labelnames=("op", "impl"),
    )
    for op, chosen in table.items():
        for impl in ("pallas", "xla"):
            gauge.labels(op, impl).set(1 if impl == chosen else 0)
    if log is not None:
        log({"event": "kernel_dispatch", "table": table,
             **info.describe()})
    else:
        summary = " ".join(f"{op}={impl}" for op, impl in table.items())
        print(f"[fengshen-tpu] kernel dispatch: {summary} "
              f"(backend={info.backend}) — {info.reason}",
              file=sys.stderr, flush=True)
    return table


# -- registrations ------------------------------------------------------
# Imported after the seam exists; the explicit register_kernel calls
# are kept here so the whole table is visible in one place.

from fengshen_tpu.ops.flash_attention import blockwise_attention  # noqa: E402
# aliased: binding the bare function name here would shadow the
# `ops.pallas.block_sparse_attention` SUBMODULE attribute that
# `import fengshen_tpu.ops.pallas.block_sparse_attention as bsa` resolves
from fengshen_tpu.ops.pallas.block_sparse_attention import (  # noqa: E402
    block_sparse_attention as _block_sparse_attention)
from fengshen_tpu.ops.pallas.decode_attention import (  # noqa: E402
    decode_attention, pallas_decode_attention, pallas_decode_eligible,
    xla_decode_attention)
from fengshen_tpu.ops.pallas.flash_attention import (  # noqa: E402
    pallas_flash_attention)
from fengshen_tpu.ops.pallas.fused_ce import (  # noqa: E402
    fused_ce_loss, pallas_fused_ce, xla_fused_ce)

register_kernel("flash_attention", "pallas", pallas_flash_attention)
register_kernel("flash_attention", "xla", blockwise_attention)
register_kernel("block_sparse_attention", "pallas", _block_sparse_attention)
# block-sparse has no standalone xla twin here: the fallback (expand the
# layout to a dense mask) lives in ops.attention.dot_product_attention
register_kernel("decode_attention", "pallas", pallas_decode_attention)
register_kernel("decode_attention", "xla", xla_decode_attention)
register_kernel("fused_ce", "pallas", pallas_fused_ce)
register_kernel("fused_ce", "xla", xla_fused_ce)

__all__ = [
    "KernelProbe", "probe", "register_kernel", "kernel_choice",
    "get_kernel", "dispatch_table", "kernel_fingerprint", "log_dispatch",
    "decode_attention", "xla_decode_attention", "pallas_decode_attention",
    "pallas_decode_eligible", "fused_ce_loss", "pallas_fused_ce",
    "xla_fused_ce", "pallas_flash_attention",
    "KERNEL_DISPATCH_METRIC", "FORCE_ENV",
]
