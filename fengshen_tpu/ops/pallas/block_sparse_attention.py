"""Pallas TPU block-sparse attention.

The TPU-native replacement for the reference's DeepSpeed sparse attention
(reference: fengshen/models/megatron/layers/utils.py:187-289 —
Fixed/Variable/LocalSlidingWindow/BigBird/BSLongformer block layouts on
Triton kernels). The layout is a static [nQ, nK] block-presence matrix
(built by fengshen_tpu.ops.masks at block granularity); absent blocks are
SKIPPED entirely — compute and HBM traffic scale with the number of present
blocks, not S².

Same streaming structure as the flash kernel: grid (B*H, nQ, nK), online
softmax in VMEM scratch, the block-presence flag prefetched to SMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _bs_kernel(layout_ref, q_ref, k_ref, v_ref, o_ref,
               acc_ref, max_ref, sum_ref,
               *, scale: float, n_kblocks: int):
    # layout_ref: [nQ, nK] int32 in SMEM; q/o: [1, blk_q, D]; k/v: [1, blk_k, D]
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        max_ref[:] = jnp.full_like(max_ref, _NEG_INF)
        sum_ref[:] = jnp.zeros_like(sum_ref)

    @pl.when(layout_ref[qb, kb] > 0)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        row_max = max_ref[:, 0]
        new_max = jnp.maximum(row_max, scores.max(axis=-1))
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[:, None])
        sum_ref[:, 0] = sum_ref[:, 0] * correction + probs.sum(axis=-1)
        max_ref[:, 0] = new_max
        acc_ref[:] = acc_ref[:] * correction[:, None] + jax.lax.dot_general(
            probs, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        out = acc_ref[:] / jnp.maximum(sum_ref[:, 0], 1e-30)[:, None]
        o_ref[0] = out.astype(o_ref.dtype)


def block_sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           layout: np.ndarray, block_size: int,
                           interpret: bool = False) -> jax.Array:
    """q/k/v: [B, S, H, D]; layout: [S//block, S//block] bool — True blocks
    are computed, False blocks skipped. Rows with no present block yield 0.
    """
    batch, q_len, num_heads, head_dim = q.shape
    k_len = k.shape[1]
    n_q, n_k = q_len // block_size, k_len // block_size
    assert layout.shape == (n_q, n_k), \
        f"layout {layout.shape} != block grid {(n_q, n_k)}"
    scale = float(1.0 / (head_dim ** 0.5))
    layout_arr = jnp.asarray(np.asarray(layout), jnp.int32)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], x.shape[3])

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    kernel = functools.partial(_bs_kernel, scale=scale, n_kblocks=n_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(qb.shape[0], n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_size, head_dim),
                               lambda b, i, j, layout: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_size, head_dim), jnp.float32),
            pltpu.VMEM((block_size, 1), jnp.float32),
            pltpu.VMEM((block_size, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qb.shape, q.dtype),
        interpret=interpret,
    )(layout_arr, qb, kb, vb)
    return (out.reshape(batch, num_heads, q_len, head_dim)
               .transpose(0, 2, 1, 3))
