"""Pallas TPU block-sparse attention (forward + fused backward).

The TPU-native replacement for the reference's DeepSpeed sparse attention
(reference: fengshen/models/megatron/layers/utils.py:187-289 —
Fixed/Variable/LocalSlidingWindow/BigBird/BSLongformer block layouts on
Triton kernels). The layout is a static [nQ, nK] block-presence matrix
(built by fengshen_tpu.ops.masks at block granularity); absent blocks are
SKIPPED entirely — compute and HBM traffic scale with the number of present
blocks, not S².

Same streaming structure as the flash kernels: online softmax in VMEM
scratch, the block-presence flags prefetched to SMEM, and a fused backward
(dkv streams q blocks per k block; dq streams k blocks per q block) gated by
the same layout flags, so training cost also scales with present blocks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _bs_fwd_kernel(layout_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   acc_ref, max_ref, sum_ref,
                   *, scale: float, n_kblocks: int):
    # layout_ref: [nQ, nK] int32 in SMEM; q/o: [1, blk_q, D]; k/v: [1, blk_k, D]
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        max_ref[:] = jnp.full_like(max_ref, _NEG_INF)
        sum_ref[:] = jnp.zeros_like(sum_ref)

    @pl.when(layout_ref[qb, kb] > 0)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        row_max = max_ref[:, 0]
        new_max = jnp.maximum(row_max, scores.max(axis=-1))
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[:, None])
        sum_ref[:, 0] = sum_ref[:, 0] * correction + probs.sum(axis=-1)
        max_ref[:, 0] = new_max
        acc_ref[:] = acc_ref[:] * correction[:, None] + jax.lax.dot_general(
            probs, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        denom = jnp.maximum(sum_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / denom[:, None]).astype(o_ref.dtype)
        # lse block is [1, 1, blk]: the singleton sublane dim satisfies
        # Mosaic's (8, 128) tiling rule (sublane == full array dim)
        lse_ref[0, 0] = max_ref[:, 0] + jnp.log(denom)


def _bs_bwd_dkv_kernel(layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                       delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                       *, scale: float, n_qblocks: int):
    # grid (BH, nK, nQ): innermost loop over q blocks per k block
    kb = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(layout_ref[qb, kb] > 0)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(scores - lse[:, None])
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qb == n_qblocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bs_bwd_dq_kernel(layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dq_acc,
                      *, scale: float, n_kblocks: int):
    # grid (BH, nQ, nK): innermost loop over k blocks per q block
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(layout_ref[qb, kb] > 0)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(scores - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _to_bh(x):
    return x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], x.shape[3])


def _from_bh(x, batch, num_heads):
    return (x.reshape(batch, num_heads, x.shape[1], x.shape[2])
             .transpose(0, 2, 1, 3))


def _bs_fwd_impl(q, k, v, layout_arr, block_size, interpret):
    batch, q_len, num_heads, head_dim = q.shape
    k_len = k.shape[1]
    n_q, n_k = q_len // block_size, k_len // block_size
    scale = float(1.0 / (head_dim ** 0.5))
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)

    kernel = functools.partial(_bs_fwd_kernel, scale=scale, n_kblocks=n_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(qb.shape[0], n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),
            pl.BlockSpec((1, 1, block_size),
                         lambda b, i, j, layout: (b, 0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_size, head_dim), jnp.float32),
            pltpu.VMEM((block_size, 1), jnp.float32),
            pltpu.VMEM((block_size, 1), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(qb.shape, q.dtype),
            jax.ShapeDtypeStruct((qb.shape[0], 1, q_len), jnp.float32),
        ],
        interpret=interpret,
    )(layout_arr, qb, kb, vb)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _block_sparse_vjp(q, k, v, layout_arr, block_size, interpret):
    out, _ = _bs_fwd_impl(q, k, v, layout_arr, block_size, interpret)
    batch, q_len, num_heads, head_dim = q.shape
    return _from_bh(out, batch, num_heads)


def _block_sparse_vjp_fwd(q, k, v, layout_arr, block_size, interpret):
    out, lse = _bs_fwd_impl(q, k, v, layout_arr, block_size, interpret)
    batch, num_heads = q.shape[0], q.shape[2]
    return _from_bh(out, batch, num_heads), (q, k, v, layout_arr, out, lse)


def _block_sparse_vjp_bwd(block_size, interpret, res, g):
    q, k, v, layout_arr, out, lse = res
    batch, q_len, num_heads, head_dim = q.shape
    k_len = k.shape[1]
    n_q, n_k = q_len // block_size, k_len // block_size
    scale = float(1.0 / (head_dim ** 0.5))
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)
    do = _to_bh(g)
    # [BH, 1, S] to match the lse layout (singleton sublane dim for Mosaic)
    delta = (do.astype(jnp.float32) *
             out.astype(jnp.float32)).sum(-1)[:, None, :]

    dkv_kernel = functools.partial(_bs_bwd_dkv_kernel, scale=scale,
                                   n_qblocks=n_q)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(qb.shape[0], n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),  # q inner
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),  # k outer
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),  # v outer
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),  # do inner
            pl.BlockSpec((1, 1, block_size),
                         lambda b, i, j, layout: (b, 0, j)),
            pl.BlockSpec((1, 1, block_size),
                         lambda b, i, j, layout: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_size, head_dim), jnp.float32),
            pltpu.VMEM((block_size, head_dim), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel, grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct(kb.shape, k.dtype),
            jax.ShapeDtypeStruct(vb.shape, v.dtype),
        ],
        interpret=interpret,
    )(layout_arr, qb, kb, vb, do, lse, delta)

    dq_kernel = functools.partial(_bs_bwd_dq_kernel, scale=scale,
                                  n_kblocks=n_k)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(qb.shape[0], n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, j, 0)),
            pl.BlockSpec((1, block_size, head_dim),
                         lambda b, i, j, layout: (b, i, 0)),
            pl.BlockSpec((1, 1, block_size),
                         lambda b, i, j, layout: (b, 0, i)),
            pl.BlockSpec((1, 1, block_size),
                         lambda b, i, j, layout: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_size, head_dim),
                               lambda b, i, j, layout: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_size, head_dim), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kernel, grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct(qb.shape, q.dtype),
        interpret=interpret,
    )(layout_arr, qb, kb, vb, do, lse, delta)

    return (_from_bh(dq, batch, num_heads), _from_bh(dk, batch, num_heads),
            _from_bh(dv, batch, num_heads), None)


_block_sparse_vjp.defvjp(_block_sparse_vjp_fwd, _block_sparse_vjp_bwd)


def block_sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           layout: np.ndarray, block_size: int,
                           interpret: bool = False) -> jax.Array:
    """q/k/v: [B, S, H, D]; layout: [S//block, S//block] bool — True blocks
    are computed, False blocks skipped. Rows with no present block yield 0.
    Differentiable: the backward runs fused Pallas kernels gated by the same
    layout, so grads also cost O(present blocks).
    """
    batch, q_len, num_heads, head_dim = q.shape
    k_len = k.shape[1]
    n_q, n_k = q_len // block_size, k_len // block_size
    assert layout.shape == (n_q, n_k), \
        f"layout {layout.shape} != block grid {(n_q, n_k)}"
    layout_arr = jnp.asarray(np.asarray(layout), jnp.int32)
    return _block_sparse_vjp(q, k, v, layout_arr, block_size, interpret)
