"""Paged decode attention: the block-table-aware fused kernel.

The stock paged decode path (`modeling_llama._update_paged_cache`)
pays a pure-bandwidth tax before attention ever runs: it gathers every
lane's blocks out of the shared KV pool into a contiguous
``[B, virt_len]`` virtual lane with ``jnp.take`` — a full copy of the
KV window per tick — and, on int8 pools, dequantizes the whole gathered
window to fp. Decode is memory-bound (arxiv 2311.03687), so that copy
is the phase's dominant cost.

This module is the ``decode_attention`` dispatch seam every decode
shape routes through (see fengshen_tpu/ops/pallas/__init__.py):

- :func:`pallas_decode_attention` — Mosaic kernel that reads the pool
  **through the block table directly**: the block-table row rides in as
  a scalar-prefetch operand, so each grid step's BlockSpec index map
  picks the lane's physical block out of HBM — no gather copy, no
  virtual-lane materialization. The int8 per-(token, head) dequant
  (``ops/int8_matmul.quantize_kv`` scales) happens in registers on the
  ``[block_size, head_dim]`` tile, and GQA reads each KV head once per
  query-head group via the index map (no HBM ``jnp.repeat``). Slot-pool
  (contiguous ``[B, max_len]``) caches reuse the same kernel by
  reshaping into ``max_len // block_size`` blocks per lane with an
  arange block table. Serves both the ``[B, 1]`` decode tick and the
  ``[B, gamma+1]`` speculative verify window (one sequential grid axis
  over blocks, online softmax across them).
- :func:`xla_decode_attention` — the stock lowering, op-for-op the
  sequence the model ran before this seam existed (take-gather →
  dequantize → GQA repeat → dense attention), so CPU tier-1 pins
  greedy decode through the dispatcher token-identical to the
  pre-kernel path.

Tiling (docs/kernels.md): the Mosaic lane dim must be a 128-multiple,
so the pallas path requires ``head_dim % 128 == 0`` and
``block_size % 128 == 0`` (the validity mask streams as
``[S, block_size]`` tiles). Pools with small pages stay on the xla
lowering — eligibility is part of the dispatch, not an error.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.int8_matmul import dequantize_kv

_NEG_INF = -1e30

#: longest query window the kernel serves — the decode tick (1) and
#: any sane speculative gamma; longer windows are prefill-shaped and
#: belong on the flash/dense paths
_MAX_QUERY_WINDOW = 8


def decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     valid: jax.Array, *,
                     k_scale: Optional[jax.Array] = None,
                     v_scale: Optional[jax.Array] = None,
                     block_table: Optional[jax.Array] = None,
                     dequant_dtype=None,
                     impl: Optional[str] = None,
                     interpret: bool = False) -> jax.Array:
    """The dispatch seam: every (layout, dtype, spec_mode) decode combo
    enters here and leaves as ``[B, S, H, D]`` attention output.

    q: ``[B, S, H, D]`` (S = 1 decode tick or gamma+1 verify window).
    k/v: ``[B, max_len, KVH, D]`` slot/lockstep cache, or the shared
    ``[num_blocks, block_size, KVH, D]`` pool when ``block_table``
    (``[B, max_blocks]`` int32) is given. int8 caches pass the
    per-(token, head) absmax scales (``k_scale``/``v_scale``) and the
    compute dtype ``dequant_dtype``. ``valid``: ``[B, S, L]`` bool over
    the (virtual) lane. ``impl`` forces ``"pallas"``/``"xla"``;
    ``None`` asks the capability probe + shape eligibility.
    """
    if impl is None:
        from fengshen_tpu.ops.pallas import probe
        use_pallas = probe().pallas_tpu and pallas_decode_eligible(
            q, k, v, k_scale=k_scale, block_table=block_table)
        impl = "pallas" if use_pallas else "xla"
    if impl == "pallas":
        return pallas_decode_attention(
            q, k, v, valid, k_scale=k_scale, v_scale=v_scale,
            block_table=block_table, dequant_dtype=dequant_dtype,
            interpret=interpret)
    return xla_decode_attention(
        q, k, v, valid, k_scale=k_scale, v_scale=v_scale,
        block_table=block_table, dequant_dtype=dequant_dtype)


def pallas_decode_eligible(q, k, v, k_scale=None,
                           block_table=None) -> bool:
    """Shape eligibility for the Mosaic kernel (the backend capability
    itself is the registry probe's job). Mirrors `_pallas_eligible` in
    ops.flash_attention: tile-aligned or stay on the stock lowering."""
    del v, k_scale
    _, s, n_heads, head_dim = q.shape
    kv_heads = k.shape[-2]
    if s > _MAX_QUERY_WINDOW:
        return False
    if n_heads % kv_heads != 0:
        return False
    if head_dim % 128 != 0:
        return False
    if block_table is not None:
        block_size = k.shape[1]
        return block_size % 128 == 0
    return k.shape[1] % 128 == 0


def xla_decode_attention(q, k, v, valid, *, k_scale=None, v_scale=None,
                         block_table=None, dequant_dtype=None):
    """The stock lowering, kept op-for-op identical to the pre-seam
    model path so greedy decode through the dispatcher is
    token-identical on CPU tier-1: paged pools gather into the
    contiguous virtual lane with ``jnp.take`` (then dequantize the
    gathered window), slot int8 caches dequantize in place, GQA
    repeats KV heads, and the dense fused softmax chain finishes."""
    dt = dequant_dtype if dequant_dtype is not None else jnp.float32
    if block_table is not None:
        num_blocks, block_size = k.shape[:2]
        batch = q.shape[0]
        virt_len = block_table.shape[-1] * block_size
        flat_k = k.reshape(num_blocks * block_size, *k.shape[2:])
        flat_v = v.reshape(num_blocks * block_size, *v.shape[2:])
        gather_idx = ((block_table * block_size)[:, :, None] +
                      jnp.arange(block_size)[None, None, :]
                      ).reshape(batch, virt_len)
        k = jnp.take(flat_k, gather_idx, axis=0)
        v = jnp.take(flat_v, gather_idx, axis=0)
        if k_scale is not None:
            flat_ks = k_scale.reshape(num_blocks * block_size, -1)
            flat_vs = v_scale.reshape(num_blocks * block_size, -1)
            k = dequantize_kv(k, jnp.take(flat_ks, gather_idx, axis=0), dt)
            v = dequantize_kv(v, jnp.take(flat_vs, gather_idx, axis=0), dt)
    elif k_scale is not None:
        k = dequantize_kv(k, k_scale, dt)
        v = dequantize_kv(v, v_scale, dt)
    n_heads, kv_heads = q.shape[2], k.shape[2]
    if kv_heads != n_heads:
        rep = n_heads // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return dot_product_attention(q, k, v, mask=valid[:, None])


def _decode_kernel(table_ref, *refs, scale, n_blocks, quantized, dt):
    """One (lane, query head, block) grid step: the BlockSpec index
    maps already routed the lane's j-th physical block into VMEM via
    ``table_ref`` — the kernel only sees ``[block_size, head_dim]``
    tiles and keeps online-softmax stats in scratch across the
    sequential block axis (same scheme as block_sparse_attention)."""
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, mask_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        (q_ref, k_ref, v_ref, mask_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0].astype(jnp.float32)          # [S, D]
    k = k_ref[0, :, 0, :]                        # [block, D]
    v = v_ref[0, :, 0, :]
    if quantized:
        # in-register per-(token, head) dequant — the pool stays int8
        # in HBM; rounding through `dt` mirrors ops.int8_matmul.
        # dequantize_kv so margins match the xla lowering
        k = (k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]).astype(dt)
        v = (v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]).astype(dt)
    scores = jax.lax.dot_general(
        q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # [S, block]
    scores = jnp.where(mask_ref[0] > 0, scores, _NEG_INF)

    m_prev, l_prev = m_ref[...], l_ref[...]              # [S, 1]
    m_new = jnp.maximum(m_prev, scores.max(-1, keepdims=True))
    correction = jnp.exp(m_prev - m_new)
    probs = jnp.exp(scores - m_new)
    l_ref[...] = l_prev * correction + probs.sum(-1, keepdims=True)
    pv = jax.lax.dot_general(
        probs, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [S, D]
    acc_ref[...] = acc_ref[...] * correction + pv
    m_ref[...] = m_new

    @pl.when(j == n_blocks - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def pallas_decode_attention(q, k, v, valid, *, k_scale=None,
                            v_scale=None, block_table=None,
                            dequant_dtype=None, block_size: int = 128,
                            interpret: bool = False):
    """Fused paged decode attention. Same contract as
    :func:`decode_attention`; slot caches (``block_table=None``) are
    viewed as ``max_len // block_size`` pool blocks per lane with an
    arange table, so one kernel serves both layouts."""
    batch, s, n_heads, head_dim = q.shape
    kv_heads = k.shape[-2]
    rep = n_heads // kv_heads
    dt = dequant_dtype if dequant_dtype is not None else jnp.float32
    quantized = k_scale is not None

    if block_table is None:
        max_len = k.shape[1]
        if max_len % block_size != 0:
            raise ValueError(
                f"slot cache length {max_len} not divisible by "
                f"block_size {block_size}; dispatch eligibility should "
                "have routed this shape to the xla lowering")
        blocks_per_lane = max_len // block_size
        k = k.reshape(batch * blocks_per_lane, block_size,
                      kv_heads, head_dim)
        v = v.reshape(batch * blocks_per_lane, block_size,
                      kv_heads, head_dim)
        if quantized:
            k_scale = k_scale.reshape(batch * blocks_per_lane,
                                      block_size, kv_heads)
            v_scale = v_scale.reshape(batch * blocks_per_lane,
                                      block_size, kv_heads)
        block_table = (jnp.arange(batch, dtype=jnp.int32)[:, None] *
                       blocks_per_lane +
                       jnp.arange(blocks_per_lane, dtype=jnp.int32)[None])
    else:
        block_size = k.shape[1]
        blocks_per_lane = block_table.shape[-1]

    qt = q.transpose(0, 2, 1, 3)                 # [B, H, S, D]
    mask = valid.astype(jnp.int32)               # [B, S, virt_len]

    def kv_map(b, h, j, table):
        # the whole point: the lane's j-th PHYSICAL block comes out of
        # the pool directly — no gather into a virtual lane
        return (table[b, j], 0, h // rep, 0)

    def scale_map(b, h, j, table):
        return (table[b, j], 0, h // rep)

    in_specs = [
        pl.BlockSpec((1, 1, s, head_dim),
                     lambda b, h, j, table: (b, h, 0, 0)),      # q
        pl.BlockSpec((1, block_size, 1, head_dim), kv_map),     # k pool
        pl.BlockSpec((1, block_size, 1, head_dim), kv_map),     # v pool
    ]
    operands = [qt, k, v]
    if quantized:
        in_specs += [pl.BlockSpec((1, block_size, 1), scale_map),
                     pl.BlockSpec((1, block_size, 1), scale_map)]
        operands += [k_scale, v_scale]
    in_specs.append(pl.BlockSpec((1, s, block_size),
                                 lambda b, h, j, table: (b, 0, j)))
    operands.append(mask)

    kernel = functools.partial(
        _decode_kernel, scale=1.0 / math.sqrt(head_dim),
        n_blocks=blocks_per_lane, quantized=quantized, dt=dt)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch, n_heads, blocks_per_lane),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, s, head_dim),
                               lambda b, h, j, table: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((s, head_dim), jnp.float32),
            pltpu.VMEM((s, 1), jnp.float32),
            pltpu.VMEM((s, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), *operands)
    return out.transpose(0, 2, 1, 3)
