"""Parameter-init dispatch.

Reference: fengshen/models/megatron/layers/init_functions.py:20-127 —
normal, scaled-normal (sigma/sqrt(2L), used for output projections),
orthogonal (fp32 QR then cast, gain sqrt(2/L)), xavier uniform/normal,
small-init (Nguyen & Salazar), wang-init (2/L/sqrt(d)), and the
`get_init_methods(config)` pair dispatch (`init_method`,
`output_layer_init_method`).

TPU-native: these return `jax.nn.initializers`-style callables
`(key, shape, dtype) -> Array`, usable directly as `flax.linen` param
initializers; the fp16-orthogonal patch the reference carries is
unnecessary because we always draw in fp32 and cast.
"""

from __future__ import annotations

import math
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

Initializer = Callable[..., jax.Array]


def init_method_normal(sigma: float) -> Initializer:
    """N(0, sigma) (reference: init_functions.py:20-27)."""
    return jax.nn.initializers.normal(stddev=sigma)


def scaled_init_method_normal(sigma: float, num_layers: int) -> Initializer:
    """N(0, sigma/sqrt(2L)) for residual-output projections
    (reference: init_functions.py:30-37)."""
    return jax.nn.initializers.normal(
        stddev=sigma / math.sqrt(2.0 * num_layers))


def orthogonal_init_method(n_layers: int = 1) -> Initializer:
    """(Semi-)orthogonal init (Saxe et al. 2013), gain sqrt(2/L)
    (reference: init_functions.py:40-78)."""
    return jax.nn.initializers.orthogonal(scale=math.sqrt(2.0 / n_layers))


def xavier_uniform_init_method() -> Initializer:
    """Glorot & Bengio (2010), uniform (reference: init_functions.py:81-88)."""
    return jax.nn.initializers.glorot_uniform()


def xavier_normal_init_method() -> Initializer:
    """Glorot & Bengio (2010), normal (reference: init_functions.py:91-98)."""
    return jax.nn.initializers.glorot_normal()


def small_init_init_method(dim: int) -> Initializer:
    """N(0, sqrt(2/(5d))) — "Transformers without Tears"
    (reference: init_functions.py:101-109)."""
    return jax.nn.initializers.normal(stddev=math.sqrt(2.0 / (5.0 * dim)))


def wang_init_method(n_layers: int, dim: int) -> Initializer:
    """N(0, 2/(L*sqrt(d))) (reference: init_functions.py:112-118)."""
    return jax.nn.initializers.normal(stddev=2.0 / n_layers / math.sqrt(dim))


_BY_NAME = {
    "normal": lambda cfg: init_method_normal(cfg.init_method_std),
    "scaled_normal": lambda cfg: scaled_init_method_normal(
        cfg.init_method_std, cfg.num_hidden_layers),
    "orthogonal": lambda cfg: orthogonal_init_method(),
    "scaled_orthogonal": lambda cfg: orthogonal_init_method(
        cfg.num_hidden_layers),
    "xavier_uniform": lambda cfg: xavier_uniform_init_method(),
    "xavier_normal": lambda cfg: xavier_normal_init_method(),
    "small_init": lambda cfg: small_init_init_method(cfg.hidden_size),
    "wang_init": lambda cfg: wang_init_method(
        cfg.num_hidden_layers, cfg.hidden_size),
}


def get_init_methods(config) -> Tuple[Initializer, Initializer]:
    """(init_method, output_layer_init_method) pair from config names
    (reference: init_functions.py:121-127 `get_init_methods`).

    `config` needs `init_method` / `output_layer_init_method` name fields
    plus `init_method_std`, `hidden_size`, `num_hidden_layers` — the same
    surface as the reference's NeoX-style config.
    """
    def _get(name: str) -> Initializer:
        factory = _BY_NAME.get(name)
        if factory is None:
            raise ValueError(
                f"unknown init method {name!r}; known: {sorted(_BY_NAME)}")
        return factory(config)

    return (_get(getattr(config, "init_method", "normal")),
            _get(getattr(config, "output_layer_init_method",
                         "scaled_normal")))


def embedding_init_method(sigma: float) -> Initializer:
    """Embedding tables stay fp32-drawn then cast (same as all of the
    above; kept as a named alias for partition-rule readability)."""
    return init_method_normal(sigma)
