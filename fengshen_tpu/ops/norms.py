"""Normalisation layers.

Reference: fengshen/models/megatron/layers/norms.py:20-63 (`get_norm` →
LayerNorm / RMSNorm / ScaleNorm, optionally apex FusedLayerNorm) and the
fused layer-norm CUDA kernel (fused_kernels/layer_norm_cuda.cpp). On TPU the
XLA compiler fuses the normalisation chain into neighbouring ops, so the
"fused kernel" is the default codegen; stats are computed in fp32 regardless
of the activation dtype (matching apex semantics).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn


class RMSNorm(nn.Module):
    """Root-mean-square norm (reference: layers/norms.py:35-53)."""

    epsilon: float = 1e-8
    dtype: Any = jnp.float32
    use_bias: bool = False

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jnp.reciprocal(jnp.sqrt(var + self.epsilon))
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        y = y * scale
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],),
                              jnp.float32)
            y = y + bias
        return y.astype(orig_dtype)


class LayerNorm(nn.Module):
    """Standard LN with fp32 statistics (reference: layers/norms.py:20-33)."""

    epsilon: float = 1e-5
    dtype: Any = jnp.float32
    use_bias: bool = True
    use_scale: bool = True

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + self.epsilon))
        if self.use_scale:
            scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                               jnp.float32)
            y = y * scale
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],),
                              jnp.float32)
            y = y + bias
        return y.astype(orig_dtype)


class ScaleNorm(nn.Module):
    """L2 scale norm (reference: layers/norms.py:55-63)."""

    epsilon: float = 1e-8
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        x32 = x.astype(jnp.float32)
        norm = jnp.linalg.norm(x32, axis=-1, keepdims=True)
        g = self.param("scale", nn.initializers.ones, (1,), jnp.float32)
        y = x32 / jnp.maximum(norm, self.epsilon) * g
        return y.astype(orig_dtype)


def get_norm(norm_type: str, epsilon: Optional[float] = None,
             dtype: Any = jnp.float32) -> nn.Module:
    """Dispatch by name (reference: layers/norms.py:20-34 `get_norm(config)`)."""
    norm_type = norm_type.lower()
    if norm_type in ("layernorm", "layer_norm", "ln"):
        return LayerNorm(epsilon=epsilon or 1e-5, dtype=dtype)
    if norm_type in ("rmsnorm", "rms_norm"):
        return RMSNorm(epsilon=epsilon or 1e-8, dtype=dtype)
    if norm_type in ("scalenorm", "scale_norm"):
        return ScaleNorm(epsilon=epsilon or 1e-8, dtype=dtype)
    raise ValueError(f"unknown norm type {norm_type!r}")
