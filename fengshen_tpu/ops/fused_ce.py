"""Chunked fused LM-head + cross-entropy (logits never fully live).

At the default bench shape (batch 28, seq 1024, vocab 32000) the fp32
logits tensor alone is ~3.7 GB of HBM — the single largest activation.
This op runs the LM-head matmul and the CE *per sequence chunk* inside a
`lax.scan`, with `jax.checkpoint` on the chunk body so the backward pass
recomputes each chunk's logits instead of storing them: peak logits
memory drops by the chunk factor, buying batch size (the real MFU lever)
at the cost of one extra head matmul in the backward.

No reference counterpart (the reference materialises full logits and
calls torch CE); this is the standard TPU fused-head pattern. Use when
the LM head is replicated; under tensor parallelism prefer
`vocab_parallel_cross_entropy`, which shards the vocab dim instead.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def fused_lm_head_ce(hidden: jax.Array, kernel: jax.Array,
                     labels: jax.Array, num_chunks: int = 8,
                     ignore_index: int = -100
                     ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """hidden [B, S, H] @ kernel [H, V] → CE against labels [B, S],
    computed in `num_chunks` sequence chunks.

    Returns (mean_loss, n_valid_tokens, n_correct) — the accuracy
    numerator comes along for free since the argmax happens while the
    chunk's logits are live.
    """
    B, S, H = hidden.shape
    num_chunks = min(num_chunks, S)
    if S % num_chunks:
        # Pad the token stream up to a multiple of num_chunks so the
        # advertised peak-HBM reduction holds at any seq len (the causal
        # variant hands us S-1, which is odd for power-of-two S).  Padded
        # rows carry ignore_index labels, so they contribute nothing to
        # loss, count, or accuracy.
        pad = num_chunks - S % num_chunks
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)),
                         constant_values=ignore_index)
        S += pad
    chunk = S // num_chunks
    hidden_c = jnp.moveaxis(
        hidden.reshape(B, num_chunks, chunk, H), 1, 0)
    labels_c = jnp.moveaxis(
        labels.reshape(B, num_chunks, chunk), 1, 0)

    @jax.checkpoint
    def chunk_stats(h, l):
        logits = (h @ kernel).astype(jnp.float32)
        valid = l != ignore_index
        safe = jnp.where(valid, l, 0)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, safe[..., None],
                                   axis=-1)[..., 0]
        loss_sum = ((logz - gold) * valid).sum()
        correct = ((logits.argmax(-1) == l) * valid).sum()
        return loss_sum, valid.sum(), correct

    def body(carry, xs):
        h, l = xs
        s, n, c = chunk_stats(h, l)
        return (carry[0] + s, carry[1] + n, carry[2] + c), None

    (loss_sum, n_valid, n_correct), _ = lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0), jnp.int32(0)),
        (hidden_c, labels_c))
    return loss_sum / jnp.maximum(n_valid, 1), n_valid, n_correct


def causal_fused_loss(hidden: jax.Array, kernel: jax.Array,
                      labels: jax.Array, num_chunks: int = 8,
                      ignore_index: int = -100):
    """Shift-by-one causal variant: hidden[:, :-1] scores labels[:, 1:]."""
    return fused_lm_head_ce(hidden[:, :-1], kernel, labels[:, 1:],
                            num_chunks=num_chunks,
                            ignore_index=ignore_index)
