"""Soft-prompt embedding (prompt tuning).

Reference: fengshen/models/megatron/layers/word_embeddings.py:157-215
(`SoftEmbedding`) — a learned [n_tokens, hidden] prompt prepended to the
token embeddings, initialised either uniformly in [-r, r] or from the
embedding rows of a tokenised init string (tiled/truncated to n_tokens);
during incremental decoding the prompt is only prepended on the first
step (it is already in the KV cache afterwards).

TPU-native: a flax module returning (embeddings, attention_mask) with
static shapes — the "first decode step" switch is the `prepend` flag the
caller sets from its cache state rather than a `layer_past.numel()` check.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def init_prompt_from_string(wte: np.ndarray, token_ids, n_tokens: int
                            ) -> np.ndarray:
    """Prompt init = embedding rows of `token_ids`, tiled/truncated to
    n_tokens (reference: word_embeddings.py:178-192)."""
    rows = np.asarray(wte)[np.asarray(token_ids, dtype=np.int32)]
    if rows.shape[0] < n_tokens:
        reps = math.ceil(n_tokens / rows.shape[0])
        rows = np.tile(rows, (reps, 1))
    return rows[:n_tokens]


class SoftEmbedding(nn.Module):
    """Learnable prompt prefix (reference: word_embeddings.py:157-215)."""

    n_tokens: int = 10
    hidden_size: int = 768
    init_range: float = 0.5
    # optional fixed init table (e.g. from init_prompt_from_string)
    init_value: Optional[np.ndarray] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, embeddings: jax.Array,
                 attention_mask: Optional[jax.Array] = None,
                 prepend: bool = True,
                 max_len: Optional[int] = None):
        if self.init_value is not None:
            table = np.asarray(self.init_value, dtype=np.float32)
            if table.shape != (self.n_tokens, self.hidden_size):
                raise ValueError(
                    f"init_value shape {table.shape} != "
                    f"({self.n_tokens}, {self.hidden_size}); tile it with "
                    "init_prompt_from_string first")
            init = lambda *_: jnp.asarray(table)
        else:
            # stored param IS the prompt: draw uniform in [-r, r) directly
            # (the reference's uniform_(-r, r), word_embeddings.py:193-195)
            init = (lambda key, shape, dtype=jnp.float32:
                    jax.random.uniform(key, shape, dtype,
                                       -self.init_range, self.init_range))
        prompt = self.param("soft_embedding_weight", init,
                            (self.n_tokens, self.hidden_size), jnp.float32)
        if not prepend:  # incremental decode: prompt already in the cache
            return embeddings, attention_mask

        batch = embeddings.shape[0]
        prompt = jnp.broadcast_to(
            prompt.astype(embeddings.dtype)[None],
            (batch, self.n_tokens, self.hidden_size))
        out = jnp.concatenate([prompt, embeddings], axis=1)
        mask = attention_mask
        if mask is not None:
            ones = jnp.ones((batch, self.n_tokens), mask.dtype)
            mask = jnp.concatenate([ones, mask], axis=1)
        if max_len is not None:  # clamp to max positions (ref :204-205)
            out = out[:, :max_len]
            if mask is not None:
                mask = mask[:, :max_len]
        return out, mask
