"""Activation functions.

Reference: fengshen/models/megatron/layers/activations.py:27-132
(`get_activation` over gelu/geglu/relu/softsign/swish/mish/silu plus a
torchscript-fused bias_gelu). On TPU, XLA fuses bias+activation into the
producing matmul, so there is no separate "fused bias-gelu" path — the plain
composition compiles to the fused kernel.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def geglu_split(x):
    """GEGLU gating over a doubled feature dim
    (reference: layers/activations.py GEGLU module)."""
    a, b = jnp.split(x, 2, axis=-1)
    return a * jax.nn.gelu(b)


_ACTIVATIONS: dict[str, Callable] = {
    # "gelu" is the exact erf form (torch F.gelu default); the tanh
    # approximation is "gelu_new", matching HF naming
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "geglu": geglu_split,
    "relu": jax.nn.relu,
    "softsign": jax.nn.soft_sign,
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "mish": mish,
    "tanh": jnp.tanh,
}


def get_activation(name: str) -> Callable:
    """Dispatch by name (reference: layers/activations.py:27-59)."""
    try:
        return _ACTIVATIONS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}")


def is_gated(name: str) -> bool:
    """Gated activations double the up-projection width
    (reference: layers/transformer.py:89-94 geglu ff_dim scaling)."""
    return name.lower() in ("geglu",)
