"""TPU-native LoRA: low-rank adapters as a SEPARATE param tree.

Covers the reference's LoRA surface and roadmap (the merge CLI
`fengshen/utils/llama_convert/fs_merge_weight.py:14-33` — its trainable
modules carry `.merge()`; LoRA/QLoRA integration is the reference's own
next-step list, `fengshen/examples/ziya_llama/README.md:59`).

Design (functional, not module-intrusive): the frozen base tree stays
untouched; `init_lora` builds a parallel tree of `(lora_a [in,r],
lora_b [r,out], lora_scale)` for every 2-D `kernel` whose path matches
a target regex, and `apply_lora` returns base-with-merged-kernels —
called INSIDE the jitted step, so XLA fuses `W + scale*A@B` into the
consumer matmul's producers and no model code changes. `lora_b` is
zero-init, so at step 0 the merged forward equals the base forward
bit-for-bit. Only `lora_a`/`lora_b` carry optimizer state (the
trainer's multi_transform freezes everything else), which is where
LoRA's memory win lives: adam moments shrink from 2×params to
2×(rank·(in+out) per target). The scale (alpha/rank) is STORED in the
tree so a later merge cannot silently use the wrong alpha.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp


def _path_keys(path) -> list[str]:
    return [getattr(k, "key", str(k)) for k in path]


def target_kernel_paths(params, target_regex: str):
    """(path-tuple-sans-'kernel', shape, dtype) for every `kernel` leaf
    whose joined path matches `target_regex` (re.search). 2-D kernels
    are plain Denses; 3-D kernels are scan_layers stacks [L, in, out]
    and get per-layer adapters ([L, in, r] / [L, r, out])."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        keys = _path_keys(path)
        if keys[-1] == "kernel" and getattr(leaf, "ndim", 0) in (2, 3) \
                and re.search(target_regex, "/".join(keys)):
            out.append((tuple(keys[:-1]), leaf.shape, leaf.dtype))
    return out


def init_lora(params, rng: jax.Array, rank: int, target_regex: str,
              alpha: float | None = None, init_std: float = 0.02):
    """Build the lora tree for `params`. alpha defaults to 2*rank (the
    common r=8/alpha=16 ratio); scale alpha/rank is stored per target."""
    if rank < 1:
        raise ValueError(f"init_lora: rank={rank} must be >= 1")
    alpha = float(2 * rank) if alpha is None else float(alpha)
    targets = target_kernel_paths(params, target_regex)
    if not targets:
        raise ValueError(
            f"init_lora: no 2-D kernel matches {target_regex!r}")
    tree: dict = {}
    rngs = jax.random.split(rng, len(targets))
    for r, (path, shape, dtype) in zip(rngs, targets):
        stack = shape[:-2]  # () for plain Dense, (L,) under scan_layers
        fin, fout = shape[-2:]
        node = tree
        for k in path:
            node = node.setdefault(k, {})
        node["lora_a"] = (jax.random.normal(r, (*stack, fin, rank),
                                            jnp.float32)
                          * init_std).astype(dtype)
        node["lora_b"] = jnp.zeros((*stack, rank, fout), dtype)
        node["lora_scale"] = jnp.asarray(alpha / rank, jnp.float32)
    return tree


def apply_lora(params, lora):
    """base-with-merged-kernels: W + scale * A@B (delta accumulated in
    fp32, cast back to W.dtype). Pure — call inside the jitted step."""
    if not isinstance(lora, dict):
        return params
    if "lora_a" in lora:
        w = params["kernel"]
        # @ batches over any leading scan_layers stack dim
        delta = (lora["lora_a"].astype(jnp.float32)
                 @ lora["lora_b"].astype(jnp.float32)) * lora["lora_scale"]
        return {**params,
                "kernel": (w.astype(jnp.float32) + delta).astype(w.dtype)}
    out = dict(params)
    for k, v in lora.items():
        out[k] = apply_lora(params[k], v)
    return out


# eager alias: merging permanently IS applying once (the reference's
# module.merge() walk, fs_merge_weight.py:7-9)
merge_lora = apply_lora


def train_path_matches(path, train_regex: str | None) -> bool:
    """Does this path WITHIN the base subtree match the fully-trained
    (`modules_to_save`) regex? The ONE predicate both the optimizer
    labels and the stop_gradient masking use — if they disagreed, a
    leaf could get adamw updates from zeroed gradients (or real
    gradients the mask then discards)."""
    return bool(train_regex) and bool(
        re.search(train_regex, "/".join(_path_keys(path))))


def lora_param_labels(params, train_regex: str | None = None):
    """Label tree for optax.multi_transform over a {'base','lora'}
    two-tree: lora_a/lora_b train, base and the stored scales freeze —
    EXCEPT base leaves matching `train_regex`, which train fully (the
    `modules_to_save` of standard LoRA: task heads are random init, so
    freezing them would leave logits a fixed random projection)."""
    def label(path, _leaf):
        keys = _path_keys(path)
        if keys and keys[0] == "lora":
            return "lora" if keys[-1] in ("lora_a", "lora_b") \
                else "freeze"
        if keys and keys[0] == "base" and \
                train_path_matches(path[1:], train_regex):
            return "lora"
        return "freeze"
    return jax.tree_util.tree_map_with_path(label, params)


def main(argv=None):
    """Merge CLI (reference: fs_merge_weight.py --input_path/
    --output_path): read a trainer checkpoint whose params are the
    {'base','lora'} two-tree, merge, and write the ONE logical orbax
    checkpoint `convert.py save_converted` produces, loadable by every
    predict/serving path."""
    import argparse
    import json
    import os

    import numpy as np
    import orbax.checkpoint as ocp

    parser = argparse.ArgumentParser(description="merge lora weight")
    parser.add_argument("--input_path", required=True,
                        help="trainer checkpoint dir (save_ckpt_path)")
    parser.add_argument("--output_path", required=True,
                        help="location to write the merged checkpoint")
    parser.add_argument("--config_path", default=None,
                        help="model config dir/json to copy alongside "
                             "(defaults to config.json inside "
                             "--input_path if present)")
    args = parser.parse_args(argv)

    mgr = ocp.CheckpointManager(os.path.abspath(args.input_path))
    step = mgr.latest_step()
    if step is None:
        raise SystemExit(f"no checkpoint steps in {args.input_path}")
    payload = mgr.restore(
        step, args=ocp.args.Composite(
            state=ocp.args.StandardRestore()))["state"]
    params = payload["params"]
    if not (isinstance(params, dict) and
            set(params) >= {"base", "lora"}):
        raise SystemExit("checkpoint params are not a {'base','lora'} "
                         "two-tree — nothing to merge")
    merged = merge_lora(params["base"], params["lora"])

    # same layout as models/llama/convert.py save_converted (the ONE
    # logical checkpoint every predict/serving path loads)
    out = os.path.abspath(args.output_path)
    os.makedirs(out, exist_ok=True)
    ckpt = ocp.StandardCheckpointer()
    ckpt.save(os.path.join(out, "params"),
              jax.tree_util.tree_map(np.asarray, merged), force=True)
    ckpt.wait_until_finished()
    with open(os.path.join(out, "parallel_meta.json"), "w") as f:
        json.dump({"intended_model_parallel_size": 1,
                   "layout": "logical (shard at load via partition "
                             "rules)"}, f)
    cfg_src = args.config_path or os.path.join(
        os.path.abspath(args.input_path), "config.json")
    if os.path.isdir(cfg_src):
        cfg_src = os.path.join(cfg_src, "config.json")
    if os.path.exists(cfg_src):
        with open(cfg_src) as f, \
                open(os.path.join(out, "config.json"), "w") as g:
            json.dump(json.load(f), g, indent=2)
    else:
        # trainer checkpoints carry no config.json — without
        # --config_path the merged dir has weights only and the
        # predict/serving loaders will refuse it; say so HERE, next to
        # the cause, not three commands later
        import sys
        print("WARNING: no config.json found (trainer checkpoints "
              "don't carry one) — pass --config_path <model dir> to "
              "make the merged checkpoint loadable by the serving "
              "paths", file=sys.stderr, flush=True)
    print(f"merged lora -> {out} (step {step})")


if __name__ == "__main__":
    main()
