"""fengshen_tpu — a TPU-native training & inference framework.

Re-implements the capabilities of the reference ``fengshen`` framework
(IDEA-CCNL/Fengshenbang-LM, surveyed in SURVEY.md) with a TPU-first design:

- ``parallel``: jax.sharding.Mesh + GSPMD partition rules replace the reference's
  Megatron ``mpu`` process groups (reference: fengshen/models/megatron/mpu/).
- ``ops``: XLA/Pallas compute kernels replace the reference's CUDA fused kernels
  (reference: fengshen/models/megatron/fused_kernels/).
- ``trainer``: a jit-compiled training loop replaces PyTorch Lightning + DeepSpeed
  (reference: fengshen/strategies/megatron_deepspeed.py).
- ``models``: the model zoo (reference: fengshen/models/).
- ``data``: host-sharded input pipeline with resumable samplers
  (reference: fengshen/data/).
- ``pipelines``/``cli``/``api``: task pipelines, console entry point, REST serving
  (reference: fengshen/pipelines, fengshen/cli, fengshen/API).
"""

__version__ = "0.1.0"
