"""Trainer: the jit-compiled training loop.

Replaces the reference's PyTorch Lightning + DeepSpeed strategy stack
(reference: fengshen/strategies/megatron_deepspeed.py and the Lightning
Trainer wiring in every example, e.g.
fengshen/examples/ziya_llama/finetune_ziya_llama.py:222-227). The
LightningModule contract (training_step / validation_step /
configure_optimizers / setup) maps onto ``TrainModule``; DeepSpeed ZeRO maps
onto optimizer-state sharding over the mesh's batch axes; activation
checkpointing maps onto ``jax.checkpoint`` policies inside the models.
"""

from fengshen_tpu.trainer.memory import (MemoryCapabilities,
                                         OffloadPolicy,
                                         probe_memory_capabilities,
                                         resolve_offload_policy)
from fengshen_tpu.trainer.module import TrainModule
from fengshen_tpu.trainer.train_state import TrainState
from fengshen_tpu.trainer.trainer import Trainer, add_trainer_args

__all__ = ["MemoryCapabilities", "OffloadPolicy", "TrainModule",
           "TrainState", "Trainer", "add_trainer_args",
           "probe_memory_capabilities", "resolve_offload_policy"]
