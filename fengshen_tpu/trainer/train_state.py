"""Train state pytree + sharded initialisation."""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from fengshen_tpu.parallel.partition import (match_partition_rules,
                                             make_shardings)


class TrainState(struct.PyTreeNode):
    """Step counter + params + optimizer state.

    The reference's equivalent is the DeepSpeedEngine wrapping module +
    FusedAdam (reference: fengshen/strategies/megatron_deepspeed.py:302-320);
    here it is a plain pytree so jit/pjit can shard and donate it.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    #: updates skipped by the in-graph step guard (non-finite loss/grads
    #: or a grad-norm spike); carried in-state so it survives
    #: steps_per_execution scans and surfaces in metrics.jsonl
    bad_step_count: jax.Array = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32))

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)

    @classmethod
    def create(cls, apply_fn, params, tx):
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params), apply_fn=apply_fn, tx=tx)


def state_shardings(rules, state: Any, mesh: Mesh):
    """NamedShardings for a whole TrainState (or its eval_shape).

    Matching runs on flattened paths, so optimizer-state entries (mu/nu
    mirror the param tree with param names embedded in the path) pick up the
    same specs as their parameters — this is the ZeRO analog: optimizer
    moments shard wherever the weights shard, plus whatever the rules put on
    the batch axes (reference capability: DeepSpeed ZeRO stages 1-3,
    fengshen/strategies/megatron_deepspeed.py:55-104).
    """
    return make_shardings(match_partition_rules(rules, state), state, mesh)


def offload_opt_state_shardings(shardings: "TrainState",
                                memory_kind: str = "pinned_host"
                                ) -> "TrainState":
    """ZeRO-offload analog: move the optimizer-state shardings to host
    memory (the capability behind the reference's '1.3B finetune in 7 GB'
    recipe, reference: fengshen/examples/classification/
    demo_classification_afqmc_erlangshen_offload.sh:9-33 — DeepSpeed
    `offload_optimizer: cpu`). XLA streams the moments host↔device around
    the optimizer update, so HBM holds only params/grads/activations."""
    host_opt = jax.tree_util.tree_map(
        lambda s: s.with_memory_kind(memory_kind), shardings.opt_state)
    return shardings.replace(opt_state=host_opt)


def create_sharded_state(init_fn: Callable[[], TrainState], rules,
                         mesh: Mesh, offload_optimizer: bool = False
                         ) -> tuple[TrainState, Any]:
    """jit `init_fn` with out_shardings from `rules` so parameters are
    created directly on their target devices (never materialised on one
    host — the analog of the reference's CPU-vs-GPU init switch,
    reference: fengshen/models/megatron/mpu/initialize.py:47-54)."""
    abstract = jax.eval_shape(init_fn)
    shardings = state_shardings(rules, abstract, mesh)
    # XLA in this build cannot emit mixed-memory-space outputs from one
    # SPMD program, so init on device and park the moments on host with an
    # outside-jit transfer
    state = jax.jit(init_fn, out_shardings=shardings)()
    if offload_optimizer:
        shardings = offload_opt_state_shardings(shardings)
        state = state.replace(opt_state=jax.device_put(
            state.opt_state, shardings.opt_state))
    return state, shardings
