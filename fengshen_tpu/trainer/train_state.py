"""Train state pytree + sharded initialisation."""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from fengshen_tpu.parallel.partition import (match_partition_rules,
                                             make_shardings)


class TrainState(struct.PyTreeNode):
    """Step counter + params + optimizer state.

    The reference's equivalent is the DeepSpeedEngine wrapping module +
    FusedAdam (reference: fengshen/strategies/megatron_deepspeed.py:302-320);
    here it is a plain pytree so jit/pjit can shard and donate it.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    #: updates skipped by the in-graph step guard (non-finite loss/grads
    #: or a grad-norm spike); carried in-state so it survives
    #: steps_per_execution scans and surfaces in metrics.jsonl
    bad_step_count: jax.Array = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32))

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)

    @classmethod
    def create(cls, apply_fn, params, tx):
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params), apply_fn=apply_fn, tx=tx)


def state_shardings(rules, state: Any, mesh: Mesh):
    """NamedShardings for a whole TrainState (or its eval_shape).

    Matching runs on flattened paths, so optimizer-state entries (mu/nu
    mirror the param tree with param names embedded in the path) pick up the
    same specs as their parameters — this is the ZeRO analog: optimizer
    moments shard wherever the weights shard, plus whatever the rules put on
    the batch axes (reference capability: DeepSpeed ZeRO stages 1-3,
    fengshen/strategies/megatron_deepspeed.py:55-104).
    """
    return make_shardings(match_partition_rules(rules, state), state, mesh)


def offload_opt_state_shardings(shardings: "TrainState",
                                memory_kind: Optional[str] = None
                                ) -> "TrainState":
    """ZeRO-offload analog: move the optimizer-state shardings to host
    memory (the capability behind the reference's '1.3B finetune in 7 GB'
    recipe, reference: fengshen/examples/classification/
    demo_classification_afqmc_erlangshen_offload.sh:9-33 — DeepSpeed
    `offload_optimizer: cpu`). XLA streams the moments host↔device around
    the optimizer update, so HBM holds only params/grads/activations.

    `memory_kind=None` resolves the host kind through the capability
    probe (docs/offload.md): `pinned_host` where the backend has it,
    `unpinned_host` otherwise — hard-coding `pinned_host` raised at
    sharding construction on backends without that space (this repo's
    CPU tier-1 backend), which is how the offload bench rungs failed
    from seed through PR 8. Explicitly passing an unsupported kind
    still raises, with the probe's findings in the message."""
    from fengshen_tpu.trainer.memory import probe_memory_capabilities
    caps = probe_memory_capabilities()
    if memory_kind is None:
        memory_kind = caps.host_kind
        if memory_kind is None:
            raise ValueError(
                "offload_opt_state_shardings: the "
                f"{caps.backend} backend supports no host memory kind "
                f"(probed: {caps.describe()['supported']}) — resolve an "
                "OffloadPolicy instead of calling this directly so the "
                "ladder can degrade to level 'none'")
    elif not caps.supports(memory_kind):
        raise ValueError(
            f"offload_opt_state_shardings: memory kind {memory_kind!r} "
            f"is unsupported on the {caps.backend} backend (probed: "
            f"{caps.describe()['supported']})")
    host_opt = jax.tree_util.tree_map(
        lambda s: s.with_memory_kind(memory_kind), shardings.opt_state)
    return shardings.replace(opt_state=host_opt)


def create_sharded_state(init_fn: Callable[[], TrainState], rules,
                         mesh: Mesh, offload_optimizer: bool = False,
                         policy: Optional[Any] = None,
                         abstract: Optional[Any] = None
                         ) -> tuple[TrainState, Any]:
    """jit `init_fn` with out_shardings from `rules` so parameters are
    created directly on their target devices (never materialised on one
    host — the analog of the reference's CPU-vs-GPU init switch,
    reference: fengshen/models/megatron/mpu/initialize.py:47-54).

    `policy` (an OffloadPolicy, docs/offload.md) decides what gets
    parked in host memory after init; the legacy `offload_optimizer`
    bool resolves a level-"opt" policy through the capability probe.
    The returned shardings carry the BETWEEN-STEP placement (moments on
    host under level "opt"+); params shardings stay device-resident —
    the offloaded step manages its own H2D/D2H explicitly."""
    if abstract is None:
        abstract = jax.eval_shape(init_fn)
    shardings = state_shardings(rules, abstract, mesh)
    if policy is None and offload_optimizer:
        from fengshen_tpu.trainer.memory import resolve_offload_policy
        policy = resolve_offload_policy("opt", abstract_state=abstract)
    # XLA in this build cannot emit mixed-memory-space outputs from one
    # SPMD program, so init on device and park the moments on host with an
    # outside-jit transfer
    state = jax.jit(init_fn, out_shardings=shardings)()
    if policy is not None and policy.offloads_opt_state:
        shardings = offload_opt_state_shardings(
            shardings, policy.opt_state_kind)
        state = state.replace(opt_state=jax.device_put(
            state.opt_state, shardings.opt_state))
    if policy is not None and policy.offloads_params:
        # level opt_master: the master/param copies ALSO park in host
        # memory between steps; the step brings them on-device only for
        # the duration of one grad+update (Trainer's offloaded step)
        host_params = jax.tree_util.tree_map(
            lambda s: s.with_memory_kind(policy.master_kind),
            shardings.params)
        state = state.replace(params=jax.device_put(
            state.params, host_params))
    return state, shardings
