"""The Trainer: sharded jit train loop with accumulation, logging, ckpt.

Replaces `pl.Trainer` + DeepSpeedStrategy
(reference: fengshen/strategies/megatron_deepspeed.py; Lightning flag surface
via `Trainer.add_argparse_args` used in every example,
e.g. fengshen/examples/ziya_llama/finetune_ziya_llama.py:191). The argparse
group below keeps the reference's flag names so example scripts port
unchanged (SURVEY.md §5.6 UX-preservation requirement).
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from fengshen_tpu.observability import (FlightRecorder, JsonlSink,
                                        StepStats, record_build_info,
                                        span)
# re-exported for compatibility (the table moved to observability.flops,
# the single home of the MFU accounting)
from fengshen_tpu.observability.flops import PEAK_FLOPS  # noqa: F401
from fengshen_tpu.parallel.mesh import MeshConfig, make_mesh, set_mesh
from fengshen_tpu.parallel.partition import make_shardings
from fengshen_tpu.trainer.module import TrainModule
from fengshen_tpu.trainer.train_state import (TrainState,
                                              create_sharded_state,
                                              state_shardings)

#: process-wide SIGTERM plumbing (see _install_preemption_handler):
#: one handler, re-pointed at the latest Trainer via weakref
_SIGTERM_STATE: dict = {"handler": None, "prev": None, "ref": None}


def _prefetch(loader, shardings, depth: int = 2):
    """Double-buffered host→device transfer: the next batch's device_put is
    issued while the current step computes (the device-prefetch contract of
    SURVEY.md §7 step 1; jax transfers are async, so holding `depth`
    in-flight batches overlaps H2D with compute).

    Yields (host_batches, device_batch, skips_at_fetch): the third
    element snapshots the loader's cumulative skipped-batch counter
    (ResilientLoader) at the moment THIS batch was fetched, so the
    consumer can credit skipped stream positions exactly when its
    training frontier passes them — not `depth` batches early."""
    import collections
    queue = collections.deque()
    for batch in loader:
        skips = getattr(loader, "skipped_total", 0)
        queue.append(([batch], jax.device_put(batch, shardings), skips))
        if len(queue) >= depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()


def _prefetch_grouped(loader, shardings, k: int, depth: int = 2):
    """K-step grouping for --steps_per_execution: stack K host batches on
    a new leading axis and issue ONE device_put; the scan-based K-step
    program then runs K optimizer steps per dispatch. Yields
    (list_of_k_host_batches, stacked_device_batch, skips_at_fetch) —
    see _prefetch for the skip-snapshot contract."""
    import collections
    queue = collections.deque()
    group = []
    for batch in loader:
        group.append(batch)
        if len(group) < k:
            continue
        try:
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *group)
        except ValueError:
            # only a genuinely RAGGED group (same tree structure,
            # mismatched leaf shapes — a loader's short final batch)
            # degrades by dropping the group loudly, like the K=1 path
            # degrades shardings. Any other ValueError (tree-structure
            # mismatch, inhomogeneous field) is a loader bug: dropping
            # every group would turn a crash into a "successful"
            # zero-step run, so it must surface.
            try:
                structs = {jax.tree_util.tree_structure(b)
                           for b in group}
                ragged = len(structs) == 1 and len(
                    {tuple(np.asarray(x).shape for x in
                           jax.tree_util.tree_leaves(b))
                     for b in group}) > 1
            except Exception:  # noqa: BLE001 — re-raise the original
                ragged = False
            if not ragged:
                raise
            print(f"[fengshen-tpu] steps_per_execution={k}: dropping a "
                  "group with mismatched batch shapes (short final "
                  "batch?)", flush=True)
            group = []
            continue
        queue.append((group, jax.device_put(stacked, shardings),
                      getattr(loader, "skipped_total", 0)))
        group = []
        if len(queue) >= depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
    if group:
        # a partial tail cannot feed the K-step program (a different
        # leading axis means a recompile) — drop it LOUDLY
        print(f"[fengshen-tpu] steps_per_execution={k}: dropping "
              f"{len(group)} tail batch(es) short of a full group",
              flush=True)


def _spanned_iter(it, name: str):
    """Time each `next()` under a trace span — the fetch side of the
    prefetch pipeline shows up as `name` in /metrics span timings and
    on profiler traces, without restructuring the for loop."""
    it = iter(it)
    while True:
        with span(name):
            try:
                item = next(it)
            except StopIteration:
                return
        yield item


def add_trainer_args(parent_parser: argparse.ArgumentParser):
    """Lightning-Trainer-compatible flag subset actually used by the
    reference examples (SURVEY.md §2.9 pattern)."""
    parser = parent_parser.add_argument_group("Trainer")
    parser.add_argument("--max_steps", default=-1, type=int)
    parser.add_argument("--max_epochs", default=1, type=int)
    parser.add_argument("--val_check_interval", default=0, type=float,
                        help="steps between validation runs (0 = per epoch)")
    parser.add_argument("--limit_val_batches", default=0, type=int)
    parser.add_argument("--log_every_n_steps", default=10, type=int)
    parser.add_argument(
        "--steps_per_execution", default=1, type=int,
        help="run K optimizer steps inside ONE jitted program "
             "(lax.scan over K stacked batches): amortizes host "
             "dispatch / interconnect round-trips when per-step launch "
             "latency is comparable to step compute. Checkpoint, "
             "validation, and preemption checks run between "
             "executions; a tail short of K batches is dropped loudly; "
             "the remaining step budget (after any checkpoint restore) "
             "is rounded DOWN to a multiple of K, and K shrinks to the "
             "remainder when fewer steps than one group are left; "
             "ignored (with a warning) under --offload_optimizer")
    parser.add_argument("--accumulate_grad_batches", default=1, type=int)
    parser.add_argument("--gradient_clip_val", default=0.0, type=float)
    parser.add_argument("--precision", default="bf16", type=str,
                        choices=["bf16", "fp32", "16", "32", "bf16-mixed"])
    parser.add_argument(
        "--offload", default="auto", type=str,
        choices=["auto", "none", "opt", "opt_master", "stream"],
        help="memory-placement ladder (docs/offload.md): none (all "
             "device-resident), opt (adam moments in host memory "
             "between steps), opt_master (moments + master/param "
             "copies host-resident), stream (per-layer parameter "
             "streaming — needs a stream-spec driver; the standard "
             "Trainer degrades it to opt_master loudly). auto probes "
             "the backend's memory kinds + byte budgets and picks the "
             "shallowest level that fits; every level falls back down "
             "the ladder when its memory kind is unsupported")
    parser.add_argument(
        "--offload_memory_kind", default="auto", type=str,
        choices=["auto", "pinned_host", "unpinned_host"],
        help="override the probe's host-memory-kind choice; forcing a "
             "kind the backend lacks raises instead of silently "
             "degrading")
    parser.add_argument(
        "--offload_optimizer", action="store_true", default=False,
        help="DEPRECATED: same as --offload=opt (kept so reference "
             "recipes parse; --offload wins when both are given). "
             "ZeRO-offload analog; reference: "
             "demo_classification_afqmc_erlangshen_offload.sh")
    parser.add_argument(
        "--profile_steps", default=None, type=str,
        help="START,END step range to capture a jax.profiler trace "
             "(saved under default_root_dir/profile; SURVEY.md §5.1)")
    parser.add_argument("--seed", default=42, type=int)
    parser.add_argument("--default_root_dir", default="./runs", type=str)
    parser.add_argument(
        "--metrics_port", default=0, type=int,
        help="serve GET /metrics (Prometheus text) from a stdlib "
             "exporter thread on this port during fit; 0 = off. Only "
             "process_index 0 of a multihost job binds the socket "
             "(docs/observability.md)")
    parser.add_argument(
        "--aot_cache_dir", default=None, type=str,
        help="persistent AOT executable cache directory "
             "(docs/aot_cache.md): the jitted train step is looked up "
             "by content address (jax version, devices, mesh axes, "
             "StableHLO) and deserialized instead of recompiled on "
             "restart/rewind; any cache failure silently falls back "
             "to a fresh compile")
    # resilience (docs/fault_tolerance.md)
    resil = parent_parser.add_argument_group("resilience")
    resil.add_argument(
        "--disable_step_guards", action="store_true", default=False,
        help="apply optimizer updates unconditionally; default is the "
             "in-graph guard that skips steps with a non-finite "
             "loss/grad norm (params and moments untouched, "
             "bad_step_count incremented)")
    resil.add_argument(
        "--skip_steps_with_grad_norm_above", default=0.0, type=float,
        help="spike guard: also skip steps whose global grad norm "
             "exceeds this threshold (0 = off)")
    resil.add_argument(
        "--max_consecutive_bad_steps", default=0, type=int,
        help="after this many consecutive guarded-away steps, restore "
             "the last checkpoint and skip the offending data window "
             "(0 = never rewind)")
    resil.add_argument(
        "--max_rewinds", default=2, type=int,
        help="abort after this many rewinds in one fit — a run that "
             "keeps diverging needs a human, not another replay")
    resil.add_argument(
        "--loader_max_retries", default=0, type=int,
        help="wrap the train/val loaders in ResilientLoader: retry "
             "transient loader errors this many times with exponential "
             "backoff before failing (0 = off)")
    resil.add_argument("--loader_backoff_base", default=0.5, type=float,
                       help="first-retry backoff in seconds; doubles "
                            "per attempt, with jitter")
    resil.add_argument(
        "--loader_skip_batches", default=0, type=int,
        help="per-epoch budget of batches that may be skipped outright "
             "after retries exhaust")
    # mesh flags (replaces strategy=... + DeepSpeed JSON)
    MeshConfig.add_argparse_args(parent_parser)
    return parent_parser


class Trainer:
    def __init__(self, args: Any, mesh_config: Optional[MeshConfig] = None,
                 logger: Optional[Any] = None):
        self.args = args
        self.mesh_config = mesh_config or MeshConfig.from_argparse_args(args)
        self.mesh = make_mesh(self.mesh_config)
        set_mesh(self.mesh)
        self.logger = logger
        self.global_step = 0
        self.consumed_samples = 0
        self.callbacks: list = []
        self._log_path = os.path.join(
            getattr(args, "default_root_dir", "./runs"), "metrics.jsonl")
        #: the unified jsonl event sink (docs/observability.md): same
        #: file, same event names, same echo format as the old ad-hoc
        #: writer — resilience/serving events flow through it too
        self._sink = JsonlSink(path=self._log_path, echo=True,
                               logger=logger)
        #: flight recorder (docs/observability.md "Flight recorder"):
        #: every _log entry also enters a bounded in-memory ring, and a
        #: step-guard rewind dumps it — the last window of step stats —
        #: as a post-mortem bundle under <root>/flightrec
        self._flightrec = FlightRecorder(
            dump_dir=os.path.join(
                getattr(args, "default_root_dir", "./runs"),
                "flightrec"))
        self._flightrec.attach("trainer", self._flight_state)
        self._metrics_server = None
        self._preempted = False
        #: deterministic fault-injection plan (tests/chaos drills); see
        #: fengshen_tpu.resilience.faults.FaultPlan.install
        self.fault_plan = None
        self._install_preemption_handler()

    def _install_preemption_handler(self) -> None:
        """SIGTERM (the preemption notice on TPU pods) sets the flag on
        the most recently constructed Trainer; the train loop
        checkpoints and exits cleanly at the next step boundary. The
        previous handler is CHAINED, not discarded — outer launchers
        (SLURM re-queue shims, pod managers) keep their own SIGTERM
        behavior. ONE process-wide handler is installed (and re-pointed
        via weakref) no matter how many Trainers a sweep driver builds,
        so neither dead Trainers nor chain links accumulate."""
        import signal
        import threading
        import weakref
        if threading.current_thread() is not threading.main_thread():
            return
        st = _SIGTERM_STATE
        st["ref"] = weakref.ref(self)
        try:
            current = signal.getsignal(signal.SIGTERM)
        except (ValueError, OSError):  # restricted env
            return
        if st["handler"] is not None and current is st["handler"]:
            self._prev_sigterm = st["prev"]
            return

        if st["handler"] is None:
            def handler(signum, frame):
                trainer = st["ref"]() if st["ref"] is not None else None
                if trainer is not None:
                    trainer._preempted = True
                if callable(st["prev"]):
                    st["prev"](signum, frame)

            st["handler"] = handler
        try:
            st["prev"] = signal.signal(signal.SIGTERM, st["handler"])
            self._prev_sigterm = st["prev"]
        except (ValueError, OSError):  # non-main thread / restricted env
            pass

    # -- step compilation ------------------------------------------------
    def _make_grad_step(self, module: TrainModule):
        """Shared gradient computation (accumulation + metrics) used by
        both the fused train step and the offloaded two-program step."""
        accum = max(int(getattr(self.args, "accumulate_grad_batches", 1)),
                    1)

        def loss_fn(params, batch, rng):
            return module.training_loss(params, batch, rng)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        # deterministic fault injection (resilience harness): poison the
        # in-graph loss at the planned step numbers so the guard path is
        # exercised exactly where a real numeric blowup would hit. The
        # plan is snapshotted at build time; disarming rebuilds the step.
        plan = getattr(self, "fault_plan", None)
        nan_steps = tuple(sorted(plan.nan_loss_at_steps)) \
            if plan is not None else ()

        def grad_step(params, batch, rng, step):
            rng = jax.random.fold_in(rng, step)
            if accum == 1:
                (loss, metrics), grads = grad_fn(params, batch, rng)
            else:
                def micro(carry, mb):
                    acc_grads, acc_loss, i = carry
                    (l, m), g = grad_fn(params, mb,
                                        jax.random.fold_in(rng, i))
                    acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads,
                                                       g)
                    return (acc_grads, acc_loss + l, i + 1), m

                batch = jax.tree_util.tree_map(
                    lambda x: x.reshape((accum, x.shape[0] // accum) +
                                        x.shape[1:]), batch)
                zero = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)
                (grads, loss, _), metrics = jax.lax.scan(
                    micro, (zero, 0.0, 0), batch)
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                loss = loss / accum
                metrics = jax.tree_util.tree_map(
                    lambda m: m.mean() if jnp.issubdtype(m.dtype,
                                                         jnp.floating)
                    else m[-1], metrics)
            metrics = dict(metrics)
            metrics["loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            if nan_steps:
                hit = jnp.any(jnp.asarray(nan_steps, jnp.int32) == step)
                metrics["loss"] = jnp.where(hit, jnp.float32(jnp.nan),
                                            metrics["loss"])
            return grads, metrics

        return grad_step

    def _guard_config(self) -> tuple[bool, float]:
        """(guards_enabled, spike_threshold) from the flags — single
        source for the fused, scanned, and offloaded step builders."""
        return (not getattr(self.args, "disable_step_guards", False),
                float(getattr(self.args,
                              "skip_steps_with_grad_norm_above", 0.0)
                      or 0.0))

    def _make_update_applier(self):
        """The (state, grads, metrics) -> (state, metrics) tail of a
        train step: guarded by default (skip non-finite/spiking
        updates in-graph, docs/fault_tolerance.md), unconditional
        under --disable_step_guards. Shared by the fused K=1 step and
        the steps_per_execution scan body."""
        from fengshen_tpu.resilience.guards import guarded_apply, step_ok
        guards_on, spike = self._guard_config()

        def apply_update(state: TrainState, grads, metrics):
            if guards_on:
                new_state = guarded_apply(state, grads,
                                          step_ok(metrics, spike))
            else:
                new_state = state.apply_gradients(grads)
            metrics["bad_step_count"] = new_state.bad_step_count
            return new_state, metrics

        return apply_update

    def _build_train_step(self, module: TrainModule, state_sh, batch_spec,
                          sample_batch=None):
        mesh = self.mesh
        grad_step = self._make_grad_step(module)
        apply_update = self._make_update_applier()

        def train_step(state: TrainState, batch, rng):
            grads, metrics = grad_step(state.params, batch, rng,
                                       state.step)
            return apply_update(state, grads, metrics)

        # fit specs to actual shapes: a debug batch smaller than the batch
        # axes degrades to replicated instead of erroring
        from fengshen_tpu.parallel.partition import _spec_fits

        def to_sharding(spec, leaf):
            shape = tuple(np.shape(leaf)) if leaf is not None else ()
            return NamedSharding(mesh, _spec_fits(spec, mesh, shape))

        if sample_batch is not None:
            batch_shardings = jax.tree_util.tree_map(
                to_sharding, batch_spec, sample_batch,
                is_leaf=lambda x: isinstance(x, P))
        else:
            batch_shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(mesh, spec), batch_spec,
                is_leaf=lambda x: isinstance(x, P))

        # eval/predict always feed single batches — stash the per-batch
        # shardings regardless of which train feed shape is returned
        self._batch_sh = batch_shardings

        spe = max(int(getattr(self.args, "steps_per_execution", 1)), 1)
        policy = getattr(self, "_offload_policy", None)
        offloaded = (policy.offloads_opt_state if policy is not None
                     else bool(getattr(self.args, "offload_optimizer",
                                       False)))
        if offloaded:
            if spe > 1:
                import sys
                print("[fengshen-tpu] --steps_per_execution is ignored "
                      "with optimizer offload (the offloaded update is "
                      "a two-program step with a host round-trip per "
                      "step — scanning K steps on-device would keep the "
                      "moments in HBM and defeat the offload)",
                      file=sys.stderr, flush=True)
            return self._build_offloaded_train_step(
                module, state_sh, batch_shardings,
                policy=policy), batch_shardings

        if spe > 1:
            # K steps per dispatch: scan over K stacked batches. The rng
            # fold_in(rng, state.step) inside grad_step makes substep
            # randomness identical to the K=1 path step for step.
            def multi_step(state: TrainState, batches, rng):
                def body(st, batch):
                    grads, m = grad_step(st.params, batch, rng, st.step)
                    return apply_update(st, grads, m)
                state, metrics = jax.lax.scan(body, state, batches)
                # same reduction policy as grad accumulation: floats
                # average over the K substeps, counts keep the last
                # (bad_step_count is cumulative, so last == end-of-group)
                metrics = jax.tree_util.tree_map(
                    lambda m: m.mean() if jnp.issubdtype(
                        m.dtype, jnp.floating) else m[-1], metrics)
                return state, metrics

            def to_stacked(spec, leaf):
                shape = (spe,) + tuple(np.shape(leaf)) \
                    if leaf is not None else ()
                return NamedSharding(
                    mesh, _spec_fits(P(None, *spec), mesh, shape))

            if sample_batch is not None:
                stacked_sh = jax.tree_util.tree_map(
                    to_stacked, batch_spec, sample_batch,
                    is_leaf=lambda x: isinstance(x, P))
            else:
                stacked_sh = jax.tree_util.tree_map(
                    lambda spec: NamedSharding(mesh, P(None, *spec)),
                    batch_spec, is_leaf=lambda x: isinstance(x, P))
            return self._maybe_aot_wrap(jax.jit(
                multi_step,
                in_shardings=(state_sh, stacked_sh, None),
                out_shardings=(state_sh, None),
                donate_argnums=(0,),
            ), "trainer/multi_step"), stacked_sh

        return self._maybe_aot_wrap(jax.jit(
            train_step,
            in_shardings=(state_sh, batch_shardings, None),
            out_shardings=(state_sh, None),
            donate_argnums=(0,),
        ), "trainer/train_step"), batch_shardings

    def _maybe_aot_wrap(self, jitted, name: str):
        """Route a jitted step through the persistent executable cache
        when --aot_cache_dir is set (docs/aot_cache.md): a restart or
        rewind deserializes the train step instead of re-paying XLA.
        The offloaded path keeps plain jit (its update program is
        built lazily per optimizer; see _build_offloaded_train_step)."""
        cache_dir = getattr(self.args, "aot_cache_dir", None)
        if not cache_dir:
            return jitted
        if getattr(self, "_aot_setup", None) is None:
            from fengshen_tpu.aot import AotConfig, AotSetup
            self._aot_setup = AotSetup(AotConfig(cache_dir=cache_dir),
                                       mesh=self.mesh, log=self._log)
        # a non-"none" placement enters the cache key — and, through
        # key_extra, the trusted-replay fingerprint (docs/offload.md):
        # placement changes the programs' transfer choreography, so a
        # stale cross-placement cache hit must be impossible. Level
        # "none" keeps key_extra EMPTY on purpose: it runs the
        # identical pre-placement program, and a non-empty extra would
        # invalidate every existing cache entry and warmup manifest of
        # users who never touch --offload
        policy = getattr(self, "_offload_policy", None)
        placement = policy.fingerprint() \
            if policy is not None and policy.level != "none" else ""
        # same bargain for the logical-axis rules table
        # (docs/sharding.md): the DEFAULT table keeps the extra empty
        # so pre-existing caches stay valid; a custom table changes how
        # every program is partitioned and must change the key
        from fengshen_tpu.sharding import (DEFAULT_LOGICAL_AXIS_RULES,
                                           get_rules, rules_fingerprint)
        if tuple(get_rules()) != tuple(DEFAULT_LOGICAL_AXIS_RULES):
            placement = f"{placement}::{rules_fingerprint()}" \
                if placement else rules_fingerprint()
        return self._aot_setup.wrap(jitted, name, key_extra=placement)

    def _build_offloaded_train_step(self, module, state_sh, batch_sh,
                                    policy=None):
        """ZeRO-offload analog: the optimizer state lives in HOST memory
        between steps, so the gradient pass runs with HBM holding only
        params + grads + activations (reference capability:
        DeepSpeed offload_optimizer, fengshen/examples/classification/
        demo_classification_afqmc_erlangshen_offload.sh:9-33). Under
        the policy's "opt_master" level the master/param copies ALSO
        park host-side between steps — device memory holds the model
        only transiently during one grad+update (docs/offload.md).

        XLA in this build cannot annotate memory spaces inside an SPMD
        program, so the H2D/D2H moves happen BETWEEN two jitted programs:
        grad_step (device-only) and update_step (donated; moments are
        device-resident only transiently during the update).
        """
        from fengshen_tpu.trainer.memory import (
            probe_memory_capabilities, resolve_offload_policy)
        if policy is None:
            policy = resolve_offload_policy("opt", log=self._log)
        grad_step = self._make_grad_step(module)
        # "bring it back on-device" = the device's DEFAULT memory kind:
        # the literal "device" raises on backends whose default space
        # has another name (the CPU backend's is "unpinned_host")
        device_kind = probe_memory_capabilities().device_memory_kind
        param_sh = state_sh.params
        opt_host_sh = jax.tree_util.tree_map(
            lambda s: s.with_memory_kind(policy.opt_state_kind),
            state_sh.opt_state)
        opt_dev_sh = jax.tree_util.tree_map(
            lambda s: s.with_memory_kind(device_kind), state_sh.opt_state)
        park_params = policy.offloads_params
        param_host_sh = jax.tree_util.tree_map(
            lambda s: s.with_memory_kind(policy.master_kind),
            param_sh) if park_params else None
        param_dev_sh = jax.tree_util.tree_map(
            lambda s: s.with_memory_kind(device_kind), param_sh)

        grad_jit = jax.jit(
            grad_step,
            in_shardings=(param_sh, batch_sh, None, None),
            out_shardings=(param_sh, None))

        def update(params, grads, opt_state, step, tx):
            updates, new_opt = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), new_opt,
                    step + 1)

        update_jit = None
        from fengshen_tpu.resilience.guards import step_ok
        guards_on, spike = self._guard_config()

        def step_fn(state, batch, rng):
            nonlocal update_jit
            # H2D (opt_master): master/param copies park host-side
            # between steps — bring them on-device for this step only
            params_dev = jax.device_put(state.params, param_dev_sh) \
                if park_params else state.params
            grads, metrics = grad_jit(params_dev, batch, rng, state.step)
            if guards_on:
                # host-side guard, same predicate as the fused step:
                # this path already pays a host round-trip per step for
                # the moments, so pulling the scalar costs no extra
                # dispatch
                if not bool(step_ok(metrics, spike)):
                    new_state = state.replace(
                        step=state.step + 1,
                        bad_step_count=state.bad_step_count + 1)
                    metrics = dict(metrics)
                    metrics["bad_step_count"] = new_state.bad_step_count
                    return new_state, metrics
            # H2D: bring the moments on-device only for the update
            opt_dev = jax.device_put(state.opt_state, opt_dev_sh)
            if update_jit is None:
                import functools
                update_jit = jax.jit(
                    functools.partial(update, tx=state.tx),
                    in_shardings=(param_sh, param_sh, opt_dev_sh, None),
                    out_shardings=(param_sh, opt_dev_sh, None),
                    donate_argnums=(0, 1, 2))
            new_params, new_opt_dev, new_step = update_jit(
                params_dev, grads, opt_dev, state.step)
            # D2H: park the moments (and under opt_master the params)
            # back in host memory
            if park_params:
                new_params = jax.device_put(new_params, param_host_sh)
            new_opt = jax.device_put(new_opt_dev, opt_host_sh)
            new_state = state.replace(step=new_step, params=new_params,
                                      opt_state=new_opt)
            metrics = dict(metrics)
            metrics["bad_step_count"] = new_state.bad_step_count
            return new_state, metrics

        return step_fn

    # -- shared state building -------------------------------------------
    def _make_init_fn(self, module: TrainModule, rng, total_steps: int,
                      eval_only: bool = False):
        """The TrainState factory fit() and validate() share. Eval-only
        states carry a zero-size optimizer (no adam moments — a model
        that only fits with --offload_optimizer must still be
        evaluable), and restore falls back to weights-only through the
        checkpoint callback's existing opt_state-mismatch path."""
        import optax

        def init_fn():
            params = module.init_params(rng)
            if eval_only:
                tx = optax.set_to_zero()
            else:
                tx, _ = module.configure_optimizers(total_steps, params)
            return TrainState.create(
                apply_fn=getattr(module, "model", None) and
                module.model.apply or (lambda *a, **k: None),
                params=params, tx=tx)

        return init_fn

    def _restore_callback(self):
        return next((c for c in self.callbacks
                     if hasattr(c, "maybe_restore")), None)

    # -- resilience ------------------------------------------------------
    def _wrap_loader(self, loader, stage: str = "train"):
        """Wrap a loader in ResilientLoader when --loader_max_retries
        asks for it (transient read errors cost a backoff, not the
        run); identity otherwise."""
        retries = int(getattr(self.args, "loader_max_retries", 0) or 0)
        skips = int(getattr(self.args, "loader_skip_batches", 0) or 0)
        # a skip budget alone still needs the wrapper — silently
        # ignoring --loader_skip_batches would be a misconfig trap
        if loader is None or (retries <= 0 and skips <= 0):
            return loader
        from fengshen_tpu.resilience import ResilientLoader
        wrapped = ResilientLoader(
            loader, max_retries=retries,
            backoff_base=float(getattr(self.args, "loader_backoff_base",
                                       0.5)),
            skip_batch_budget=skips,
            log=self._log, stage=stage,
            # per-host jitter: identical seeds would re-hit the storage
            # in lockstep from every process on a retry (the thundering
            # herd the jitter exists to break up)
            jitter_seed=jax.process_index())
        if skips > 0 and stage == "train" and not wrapped.resumable:
            # the budget only works on loaders that can be advanced
            # past a poison batch — say so instead of silently never
            # skipping (e.g. --sampler_type single)
            self._log({"event": "loader_skip_budget_inert",
                       "reason": "train loader is not mid-epoch "
                                 "resumable; skips need the stateful "
                                 "random sampler"})
        return wrapped

    def _rewind(self, state: TrainState, ckpt_cb, bad_steps: int
                ) -> TrainState:
        """Rewind-on-divergence: restore the last checkpoint (its params
        predate the bad window — the step guard skipped every bad
        update) and advance consumed_samples PAST the offending data so
        the replay sees fresh batches. Raises instead of replaying
        forever: a run that keeps diverging needs a human."""
        if ckpt_cb is None:
            raise RuntimeError(
                f"{bad_steps} consecutive bad steps at step "
                f"{self.global_step} and no checkpoint callback to "
                "rewind from — aborting instead of optimizing on "
                "garbage")
        if self._rewinds_left <= 0:
            raise RuntimeError(
                f"rewind budget exhausted (--max_rewinds="
                f"{getattr(self.args, 'max_rewinds', 2)}) and still "
                f"seeing {bad_steps} consecutive bad steps at step "
                f"{self.global_step}")
        self._rewinds_left -= 1
        pre_step = int(self.global_step)
        pre_consumed = int(self.consumed_samples)
        if hasattr(ckpt_cb, "wait"):
            ckpt_cb.wait()  # an in-flight async save must land first
        # rewind to THIS run's latest checkpoint: maybe_restore reads
        # load_ckpt_path, which may point at a stale warm-start dir —
        # the run's own saves are the only valid rewind targets
        orig_load = getattr(ckpt_cb, "load_path", None)
        if getattr(ckpt_cb, "save_path", None):
            ckpt_cb.load_path = ckpt_cb.save_path
        try:
            restored = ckpt_cb.maybe_restore(state, self)
        finally:
            if hasattr(ckpt_cb, "load_path"):
                ckpt_cb.load_path = orig_load
        if restored is state and int(self.global_step) == pre_step:
            raise RuntimeError(
                f"rewind after {bad_steps} consecutive bad steps found "
                "no restorable checkpoint (set --save_ckpt_path/"
                "--every_n_train_steps)")
        # the window [checkpoint, pre_step] produced the divergence —
        # keep the data cursor ahead of it
        self.consumed_samples = max(pre_consumed,
                                    int(self.consumed_samples))
        if getattr(self, "_stepstats", None) is not None:
            # goodput ledger: the replayed window counts against the
            # attempted-steps denominator
            self._stepstats.record_rewind(pre_step,
                                          int(self.global_step))
        self._log({"event": "rewind", "from_step": pre_step,
                   "to_step": int(self.global_step),
                   "bad_steps": int(bad_steps),
                   "consumed_samples": int(self.consumed_samples),
                   "rewinds_left": self._rewinds_left})
        try:
            # post-mortem bundle (docs/fault_tolerance.md): the ring
            # holds the step entries — tokens/s, mfu, goodput,
            # bad_step_count — leading into the divergence. Process-0
            # only, like every other writer (a collective divergence
            # would otherwise have N hosts clobbering one bundle path)
            if jax.process_index() == 0:
                from fengshen_tpu.observability import get_registry
                self._flightrec.snapshot_metrics([get_registry()],
                                                 force=True)
                self._flightrec.dump(
                    reason="rewind",
                    extra={"from_step": pre_step,
                           "to_step": int(self.global_step),
                           "bad_steps": int(bad_steps)})
        except Exception as e:  # noqa: BLE001 — telemetry must never
            # fail the rewind that is saving the run
            self._log({"event": "flightrec_dump_error",
                       "error": str(e)[:200]})
        return restored

    # -- predict state ---------------------------------------------------
    def restore_for_predict(self, module: TrainModule,
                            stage: str = "predict") -> TrainState:
        """Build + restore an eval-only TrainState WITHOUT running a
        validation sweep — the cheap entry for predict-only drivers
        (e.g. classification --do_predict_only), and the shared
        state-construction path of validate()."""
        module.setup(stage)
        rng = jax.random.PRNGKey(getattr(self.args, "seed", 42))
        state, state_sh = create_sharded_state(
            self._make_init_fn(module, rng, 1, eval_only=True),
            module.partition_rules(), self.mesh)
        self._state_sh = state_sh
        ckpt_cb = self._restore_callback()
        prev_step = self.global_step
        if ckpt_cb is not None:
            state = ckpt_cb.maybe_restore(state, self, weights_only=True)
        if self.global_step == prev_step:
            # restore silently skipped (no checkpoint found): the run
            # proceeds on init_params — legitimate for HF-imported
            # weights, surprising otherwise, so SAY it
            self._log({"event": f"{stage}_no_checkpoint_restored"})
        return state

    # -- validate --------------------------------------------------------
    def validate(self, module: TrainModule, datamodule) -> TrainState:
        """Eval-only entry (the reference's `--do_eval_only` path,
        reference: fengshen/examples/pretrain_t5/
        pretrain_mt5_small_predict.sh): build/restore the state, run ONE
        validation sweep over the val loader, no training."""
        args = self.args
        datamodule.trainer = self
        loader = getattr(datamodule, "val_dataloader", lambda: None)()
        if loader is None:
            # mid-fit a missing val loader is skippable; here it IS the
            # whole job
            raise ValueError(
                "validate() has no validation data — pass --val_file / "
                "a 'validation' split (val_datasets_field="
                f"{getattr(args, 'val_datasets_field', 'validation')!r})")
        state = self.restore_for_predict(module, stage="validate")
        rng = jax.random.PRNGKey(getattr(args, "seed", 42))
        self._log({"event": "validate_start",
                   "step": self.global_step})
        self._run_validation(module, datamodule, state, rng)
        return state

    # -- fit -------------------------------------------------------------
    def fit(self, module: TrainModule, datamodule) -> TrainState:
        try:
            return self._fit(module, datamodule)
        finally:
            # the --metrics_port exporter must not outlive the fit: a
            # leaked daemon socket serves stale metrics and makes the
            # next Trainer on the same port die with EADDRINUSE
            self._close_metrics_server()

    def _close_metrics_server(self) -> None:
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None

    def _fit(self, module: TrainModule, datamodule) -> TrainState:
        args = self.args
        module.setup("fit")
        # wire the datamodule so resumable samplers can read
        # consumed_samples (reference: universal_datamodule.py:8-17)
        datamodule.trainer = self
        rng = jax.random.PRNGKey(getattr(args, "seed", 42))

        meta_loader = datamodule.train_dataloader()
        dataset_len = getattr(meta_loader, "num_samples",
                              None) or len(meta_loader)
        world_batch = getattr(meta_loader, "global_batch_size", 1)
        from fengshen_tpu.models.model_utils import get_total_steps
        total_steps = get_total_steps(args, dataset_len, world_batch)

        max_steps = getattr(args, "max_steps", -1)
        if max_steps is None or max_steps <= 0:
            max_steps = total_steps

        # build sharded state (peek never advances the stateful sampler)
        sample_batch = meta_loader.peek() if hasattr(meta_loader, "peek") \
            else next(iter(meta_loader))
        rules = module.partition_rules()

        # memory placement (docs/offload.md): probe the backend's
        # memory kinds, size the state from eval_shape (no buffers),
        # resolve the offload ladder level BEFORE anything compiles —
        # the policy decides the state shardings, which step program is
        # built, and the AOT cache key
        from fengshen_tpu.trainer.memory import (offload_request_from_args,
                                                 record_offload_metrics,
                                                 resolve_offload_policy)
        init_fn = self._make_init_fn(module, rng, total_steps)
        abstract = jax.eval_shape(init_fn)
        mesh_shape = dict(self.mesh.shape)
        policy = resolve_offload_policy(
            offload_request_from_args(args),
            abstract_state=abstract,
            memory_kind=getattr(args, "offload_memory_kind", "auto"),
            can_stream=False,  # the standard Trainer has no stream spec
            # one state replica shards over the model axes only — the
            # data/sequence axes REPLICATE it, so counting every device
            # would overestimate capacity by the DP factor
            state_shard_ways=(mesh_shape.get("fsdp", 1) *
                              mesh_shape.get("tensor", 1) *
                              mesh_shape.get("pipe", 1)),
            log=self._log)
        self._offload_policy = policy
        spe = 1 if policy.offloads_opt_state else \
            max(int(getattr(args, "steps_per_execution", 1)), 1)

        state, state_sh = create_sharded_state(
            init_fn, rules, self.mesh, policy=policy, abstract=abstract)

        # observability (docs/observability.md): ladder level, probed
        # kinds, and the bytes actually parked host-side between steps
        host_bytes = 0
        if policy.offloads_opt_state:
            host_bytes += sum(
                leaf.nbytes
                for leaf in jax.tree_util.tree_leaves(state.opt_state)
                if hasattr(leaf, "nbytes"))
        if policy.offloads_params:
            host_bytes += sum(
                leaf.nbytes
                for leaf in jax.tree_util.tree_leaves(state.params)
                if hasattr(leaf, "nbytes"))
        record_offload_metrics(policy, host_resident_bytes=host_bytes)
        _, self._schedule = module.configure_optimizers(total_steps,
                                                        state.params)

        # restore (updates self.global_step / self.consumed_samples)
        ckpt_cb = self._restore_callback()
        if ckpt_cb is not None:
            state = ckpt_cb.maybe_restore(state, self)
        # K-step programs only stop on execution boundaries, so the
        # REMAINING budget (after any restore — a fresh run resumes at
        # 0) must be a multiple of K. Align ONCE, here, from the
        # original max_steps: aligning before restore and again after
        # double-rounds and can silently lose up to 2(K-1) steps.
        # Clamp/round DOWN and say so rather than overshooting the LR
        # schedule (parity contract with the K=1 run); the step program
        # is built below, after this point, so a clamped K takes effect.
        remaining = max_steps - self.global_step
        if spe > 1 and 0 < remaining < spe:
            # fewer steps left than one K-group: shrink K to the
            # remainder rather than either overshooting the schedule by
            # a full group or rounding the tail steps away
            self._log({"event": "steps_per_execution_clamped",
                       "from": spe, "to": int(remaining),
                       "resumed_at": int(self.global_step)})
            spe = int(remaining)
            args.steps_per_execution = spe
        elif spe > 1 and remaining > 0 and remaining % spe:
            # not K-aligned: round the budget down to a whole number of
            # K-groups past the current step
            new_max = self.global_step + (remaining // spe) * spe
            self._log({"event": "max_steps_rounded_down",
                       "from": int(max_steps), "to": int(new_max),
                       "steps_per_execution": spe,
                       "resumed_at": int(self.global_step)})
            max_steps = new_max
        # (re)create the train loader AFTER restore so the resumable
        # sampler starts from the restored consumed_samples
        train_loader = self._wrap_loader(datamodule.train_dataloader())

        batch_spec = module.batch_spec(sample_batch)
        step_fn, batch_sh = self._build_train_step(module, state_sh,
                                                   batch_spec, sample_batch)
        self._state_sh = state_sh
        # eval/predict always feed SINGLE batches — under
        # steps_per_execution>1 the train feed (batch_sh) is stacked;
        # _build_train_step stashed the per-batch shardings for the
        # validation path in self._batch_sh either way

        n_params = sum(np.prod(p.shape) for p in
                       jax.tree_util.tree_leaves(state.params))
        self._log({"event": "fit_start", "n_params": int(n_params),
                   "total_steps": int(total_steps),
                   "mesh": dict(self.mesh.shape)})

        # step-stats pipeline (docs/observability.md): tokens/s, MFU
        # against the resolved per-chip peak (always finite — nominal
        # fallback off-TPU), and goodput fed by the guards'
        # bad_step_count + the rewind ledger
        flops_per_tok = module.flops_per_token() or 6.0 * float(n_params)
        self._stepstats = StepStats(
            flops_per_token=flops_per_tok,
            n_devices=len(jax.devices()),
            device_kind=jax.devices()[0].device_kind)
        record_build_info()
        self._maybe_start_metrics_server()
        log_every = max(int(getattr(args, "log_every_n_steps", 10)), 1)
        val_interval = int(getattr(args, "val_check_interval", 0) or 0)

        profile_range = None
        if getattr(args, "profile_steps", None):
            lo, hi = (int(x) for x in str(args.profile_steps).split(","))
            profile_range = (lo, hi)
            self._profiling = False

        def crossed(prev: int, cur: int, every: int) -> bool:
            # did [prev+1, cur] contain a multiple of `every`? (an
            # execution advances global_step by spe, which may jump
            # over the exact multiple)
            return every > 0 and (cur // every) > (prev // every)

        # rewind-on-divergence bookkeeping (docs/fault_tolerance.md):
        # only armed via --max_consecutive_bad_steps, because detecting
        # the consecutive run needs the cumulative bad_step_count pulled
        # to the host every execution (a per-step device sync the
        # default fast path must not pay)
        max_consec = int(getattr(args, "max_consecutive_bad_steps", 0)
                         or 0)
        if max_consec and getattr(args, "disable_step_guards", False):
            raise ValueError("--max_consecutive_bad_steps needs the step "
                             "guards; drop --disable_step_guards")
        self._rewinds_left = int(getattr(args, "max_rewinds", 2))
        consec_bad = 0
        prev_bad_total = int(state.bad_step_count) if max_consec else 0
        skips_credited = 0  # loader skips already folded into consumed

        epoch = 0
        # a run restored at (or past) its step budget must not execute
        # even one group — the loop body only checks max_steps AFTER an
        # execution, which would overshoot the LR schedule
        done = self.global_step >= max_steps
        while not done:
            if hasattr(train_loader, "set_epoch"):
                train_loader.set_epoch(epoch)
            feed = (_prefetch(train_loader, batch_sh) if spe == 1 else
                    _prefetch_grouped(train_loader, batch_sh, spe))
            rewound = False
            for group, device_batch, skips_snap in _spanned_iter(
                    feed, "train/load"):
                if profile_range is not None:
                    self._maybe_profile(profile_range)
                with span("train/step"):
                    state, metrics = step_fn(state, device_batch, rng)
                prev_step = int(self.global_step)
                self.global_step = prev_step + len(group)
                # callbacks (e.g. every-n checkpointing) need the span
                # of this execution to detect crossed boundaries
                self.prev_global_step = prev_step
                self.consumed_samples += world_batch * len(group)
                # credit skipped poison batches exactly when the
                # training frontier passes them (the fetch-time
                # snapshot), so a checkpoint taken inside the prefetch
                # window never records a cursor ahead of the data
                # actually trained on
                if skips_snap > skips_credited:
                    self.consumed_samples += world_batch * (
                        skips_snap - skips_credited)
                    skips_credited = skips_snap
                self._stepstats.record_execution(
                    len(group), sum(module.tokens_in_batch(b)
                                    for b in group))

                if crossed(prev_step, self.global_step, log_every):
                    metrics = {k: float(v) for k, v in metrics.items()}
                    entry = {"step": self.global_step,
                             "lr": float(self._schedule(self.global_step)),
                             "consumed_samples": self.consumed_samples,
                             **metrics}
                    # tokens_per_sec / mfu / goodput over the window
                    # since the last entry; closes the window
                    entry.update(self._stepstats.window_entry(
                        self.global_step,
                        bad_step_count=int(
                            metrics.get("bad_step_count", 0))))
                    self._log(entry)

                if crossed(prev_step, self.global_step, val_interval):
                    self._run_validation(module, datamodule, state, rng)
                for cb in self.callbacks:
                    if hasattr(cb, "on_train_step_end"):
                        # every-n checkpointing lives here; the span
                        # makes save stalls visible next to step time
                        with span("train/checkpoint"):
                            cb.on_train_step_end(self, state)
                if max_consec:
                    bad_total = int(metrics["bad_step_count"])
                    delta, prev_bad_total = (bad_total - prev_bad_total,
                                             bad_total)
                    if delta >= len(group):
                        consec_bad += len(group)  # whole execution bad
                    elif delta > 0:
                        # mixed group: substep order is unknown from the
                        # host; assume the bad run is trailing
                        consec_bad = delta
                    else:
                        consec_bad = 0
                    if consec_bad >= max_consec:
                        state = self._rewind(state, ckpt_cb, consec_bad)
                        prev_bad_total = int(state.bad_step_count)
                        consec_bad = 0
                        plan = getattr(self, "fault_plan", None)
                        if plan is not None and plan.nan_loss_at_steps \
                                and plan.clear_nan_on_rewind:
                            # replayed step numbers must not re-fire the
                            # injected fault: disarm and rebuild the
                            # step program without the injection
                            plan.disarm_nan()
                            step_fn, batch_sh = self._build_train_step(
                                module, state_sh, batch_spec,
                                sample_batch)
                        rewound = True
                        break
                if self._preempted:
                    # preemption-aware autosave (SURVEY.md §5.3: TPU pods
                    # preempt; the reference only had SLURM re-queue).
                    # MUST flush: an async save lost to process exit is
                    # no save at all
                    if ckpt_cb is not None:
                        with span("train/checkpoint"):
                            try:
                                ckpt_cb.save(state, self, sync=True)
                            except TypeError:  # cb without sync kwarg
                                ckpt_cb.save(state, self)
                    self._log({"event": "preempted_saved",
                               "step": self.global_step})
                    return state
                if self.global_step >= max_steps:
                    done = True
                    break
            if rewound:
                # same epoch, fresh loader: the resumable sampler picks
                # up from the advanced consumed_samples, skipping the
                # window that produced the bad steps
                train_loader = self._wrap_loader(
                    datamodule.train_dataloader())
                skips_credited = 0  # fresh wrapper, fresh counter
                continue
            # a skip at the very end of the epoch has no later batch to
            # carry its snapshot — settle the remainder here so the
            # next epoch's loader starts past it. ONLY on a natural
            # epoch end: after a max_steps break the uncredited skips
            # sit beyond the training frontier (prefetch window) and
            # must not advance the cursor a resume will trust
            if not done:
                tail_skips = getattr(train_loader, "skipped_total", 0)
                if tail_skips > skips_credited:
                    self.consumed_samples += world_batch * (
                        tail_skips - skips_credited)
                    skips_credited = tail_skips
            epoch += 1
            if getattr(args, "max_epochs", 1) and \
                    epoch >= max(getattr(args, "max_epochs", 1), 1):
                done = True
            if not val_interval:
                self._run_validation(module, datamodule, state, rng)

        if profile_range is not None and getattr(self, "_profiling", False):
            jax.profiler.stop_trace()
            self._profiling = False
        for cb in self.callbacks:
            if hasattr(cb, "on_fit_end"):
                cb.on_fit_end(self, state)
        self._log({"event": "fit_end", "step": self.global_step})
        return state

    def _maybe_profile(self, profile_range: tuple) -> None:
        """Start/stop a jax.profiler trace over the configured step window
        (SURVEY.md §5.1: trace-guided perf work instead of guesses)."""
        lo, hi = profile_range
        if getattr(self, "_profile_done", False):
            return
        # >= lo (not a range test): under --steps_per_execution the
        # observed global_step values can jump clean over [lo, hi) — a
        # late start still captures at least one full execution
        if not self._profiling and self.global_step >= lo:
            path = os.path.join(
                getattr(self.args, "default_root_dir", "./runs"), "profile")
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            self._profiling = True
            self._log({"event": "profile_start", "step": self.global_step,
                       "path": path})
        elif self._profiling and self.global_step >= hi:
            jax.profiler.stop_trace()
            self._profiling = False
            self._profile_done = True
            self._log({"event": "profile_stop", "step": self.global_step})

    # -- predict ---------------------------------------------------------
    def predict(self, module: TrainModule, dataloader, state=None,
                params=None, **kwargs) -> list:
        """Prediction loop over `module.predict_step`
        (reference: the Lightning predict path used for TP generation,
        fengshen/examples/ziya_llama/finetune_ziya_llama.py:155-176 +
        strategies/megatron_deepspeed.py:371-399)."""
        if params is None:
            params = state.params if state is not None else None
        if params is None:
            raise ValueError("predict needs state or params")
        if not hasattr(module, "predict_step"):
            raise AttributeError(
                f"{type(module).__name__} defines no predict_step")
        # jit + shard the predict step when the module opts in (generation
        # loops with python control flow stay eager); batches ride the
        # same shardings as training (VERDICT r1 weak #8)
        step = module.predict_step
        if getattr(module, "jit_predict", False):
            import functools
            step = jax.jit(functools.partial(module.predict_step, **kwargs))
            kwargs = {}
        outputs = []
        warned_fallback = False
        for batch in dataloader:
            if getattr(self, "_batch_sh", None) is not None:
                try:
                    batch = jax.device_put(batch, self._batch_sh)
                except (ValueError, TypeError) as e:
                    # batch structure differs from training — running
                    # un-sharded is correct but quietly gathers onto one
                    # device on a pod, so say so ONCE (same contract as
                    # _run_validation's val_shard_fallback)
                    if not warned_fallback:
                        warned_fallback = True
                        self._log({"event": "predict_shard_fallback",
                                   "step": self.global_step,
                                   "error": str(e)[:200]})
            outputs.append(step(params, batch, **kwargs))
        return outputs

    # -- validation ------------------------------------------------------
    def _run_validation(self, module, datamodule, state, rng):
        loader = self._wrap_loader(
            getattr(datamodule, "val_dataloader", lambda: None)(),
            stage="val")
        if loader is None:
            return
        val_params = state.params
        policy = getattr(self, "_offload_policy", None)
        if policy is not None and policy.offloads_params and \
                getattr(self, "_state_sh", None) is not None:
            # opt_master parks params in HOST memory between steps
            # (docs/offload.md), but the cached val jit's in_shardings
            # are device-resident — bring one device copy up for the
            # sweep (dropped when the sweep ends). Without this, any
            # backend whose host kind differs from the device default
            # would mismatch and silently demote every batch to the
            # inferred-sharding fallback jit.
            device_kind = policy.caps.device_memory_kind
            val_params = jax.device_put(
                state.params,
                jax.tree_util.tree_map(
                    lambda s: s.with_memory_kind(device_kind),
                    self._state_sh.params))
        losses, limit = [], getattr(self.args, "limit_val_batches", 0)
        # cache the compiled val step across invocations; params ride the
        # training shardings so validation never gathers the model onto
        # one device (VERDICT r1 weak #8)
        if getattr(self, "_val_fn_module", None) is not module:
            param_sh = getattr(self, "_state_sh", None)
            if param_sh is not None:
                self._val_fn = jax.jit(
                    module.validation_loss,
                    in_shardings=(param_sh.params,
                                  getattr(self, "_batch_sh", None), None))
            else:
                self._val_fn = jax.jit(module.validation_loss)
            self._val_fn_module = module
        val_fn = self._val_fn
        # per-metric (weighted sum, weight) so a metric emitted by only
        # some batches is averaged over ITS batches, and per-batch means
        # (accuracies) are weighted by batch rows rather than skewed by a
        # short tail batch (ADVICE r4).  Count-like metrics (n_*, *_sum,
        # *_count) are summed, not averaged.
        metric_sums: dict = {}

        def _is_countlike(k: str) -> bool:
            base = k[4:] if k.startswith("val_") else k
            return (base.startswith("n_") or base.endswith("_sum")
                    or base.endswith("_count"))

        def _batch_rows(batch) -> float:
            for v in jax.tree_util.tree_leaves(batch):
                if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1:
                    return float(v.shape[0])
            return 1.0

        def _accumulate(metrics, weight):
            for k, v in (metrics or {}).items():
                try:
                    v = float(v)
                except (TypeError, ValueError):
                    continue  # non-scalar diagnostic; skip
                s, w = metric_sums.get(k, (0.0, 0.0))
                if _is_countlike(k):
                    metric_sums[k] = (s + v, -1.0)
                else:
                    metric_sums[k] = (s + v * weight, w + weight)

        for i, batch in enumerate(loader):
            if limit and i >= limit:
                break
            rows = _batch_rows(batch)
            try:
                loss, metrics = val_fn(val_params, batch, rng)
            except (TypeError, ValueError) as e:
                # this batch doesn't fit the train batch spec — run IT on a
                # separately cached inferred-sharding jit, but keep the
                # sharded val_fn for subsequent conforming batches
                if not hasattr(self, "_val_fn_plain"):
                    self._val_fn_plain = jax.jit(module.validation_loss)
                    self._log({"event": "val_shard_fallback",
                               "step": self.global_step,
                               "error": str(e)[:200]})
                loss, metrics = self._val_fn_plain(val_params, batch,
                                                   rng)
            _accumulate(metrics, rows)
            losses.append((float(loss), rows))
        if losses:
            total_rows = sum(w for _, w in losses)
            entry = {"step": self.global_step,
                     "val_loss": sum(l * w for l, w in losses)
                     / max(total_rows, 1.0)}
            for k, (total, w) in metric_sums.items():
                key = k if k.startswith("val_") else f"val_{k}"
                entry[key] = total if w < 0 else total / max(w, 1e-9)
            self._log(entry)

    # -- logging ---------------------------------------------------------
    def _log(self, entry: dict) -> None:
        """One structured event. Delegates to the unified JsonlSink
        (process-0 gating, jsonl write, console echo, logger bridge) —
        kept as a method because resilience loaders and callbacks hold
        `log=self._log` references. Every entry also enters the flight
        recorder's ring so a rewind dump carries the recent step
        trajectory."""
        self._flightrec.record(entry)
        self._sink(entry)

    def _flight_state(self) -> dict:
        """The flight recorder's trainer provider: cursor state + the
        scalar run config (the post-mortem bundle's `trainer.json`)."""
        return {
            "step": int(self.global_step),
            "consumed_samples": int(self.consumed_samples),
            "rewinds_left": int(getattr(self, "_rewinds_left", 0) or 0),
            "args": {k: v for k, v in
                     sorted(getattr(self.args, "__dict__", {}).items())
                     if isinstance(v, (bool, int, float, str,
                                       type(None)))},
        }

    def _maybe_start_metrics_server(self) -> None:
        """`--metrics_port N`: a stdlib exporter thread serving
        GET /metrics for the duration of the job; process-0-gated (the
        gate lives in start_metrics_server)."""
        port = int(getattr(self.args, "metrics_port", 0) or 0)
        if not port or self._metrics_server is not None:
            return
        from fengshen_tpu.observability import start_metrics_server
        self._metrics_server = start_metrics_server(port)
        if self._metrics_server is not None:
            self._log({"event": "metrics_server_started",
                       "port": self._metrics_server.port})
