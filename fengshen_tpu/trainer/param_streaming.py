"""Host-resident parameter streaming — the ZeRO-3 / param-offload analog.

The reference's offload surface moves BOTH optimizer state and params to
CPU (reference: fengshen/strategies/megatron_deepspeed.py:55-104
`offload_optimizer` / `offload_param` device=cpu|nvme; the "7 GB finetune
of 1.3B" recipe fengshen/examples/classification/
demo_classification_afqmc_erlangshen_offload.sh:9-33). The existing
`--offload_optimizer` parks the adam moments host-side; this module goes
the rest of the way: PARAMETERS live in host memory and stream to HBM one
transformer layer at a time inside the step, so device memory holds one
layer's params + grads + moments plus the boundary activations — never
the whole model.

Mechanism (XLA in this build cannot annotate memory spaces inside one
SPMD program — same constraint as the offloaded optimizer step, see
trainer.py `_build_offloaded_train_step`): the step is decomposed into
per-layer jitted programs with H2D/D2H transfers between them.

  forward   h0 = bottom(p_bot, batch)           # embeddings
            h_{l+1} = layer(p_l ⇐ host, h_l)    # one layer in HBM
  top       loss, g_top, g_h = grad(top)(p_top, h_L, batch)
  backward  g_l, g_h = vjp(layer)(p_l ⇐ host, h_l, g_h)   # recompute
            g_l ⇒ host                                    # grads park
  update    for every part: p, g, m, v ⇐ host → adamw → ⇒ host

The update applies optax-equivalent clip_by_global_norm + AdamW (bias
correction, decoupled weight decay) one part at a time, so global-norm
clipping stays exact while HBM never holds more than one part's
(p, g, m, v) quadruple. The price is one extra forward (vjp recompute —
the same trade `jax.checkpoint` makes) plus PCIe/DMA traffic per layer;
the reward is fitting models whose params + moments dwarf HBM.

Two family splits ship: the flagship LLaMA causal LM and the
classification TaskModel over a MegatronBert backbone (the AFQMC 7 GB
recipe). Both are parity-tested against the monolithic jitted step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class StreamSpec:
    """A model factored into bottom / repeated layer / top segments.

    bottom_fn(p, batch, rng) -> h0
    layer_fn(p, h, batch, rng) -> h
    top_fn(p, h, batch, rng) -> (loss, metrics_dict)
    """

    bottom_fn: Callable
    layer_fn: Callable
    top_fn: Callable
    bottom: Any
    layers: list
    top: Any


def _host(tree):
    return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))


def _zeros_like_host(tree, dtype=None):
    return jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape, dtype if dtype is not None
                           else x.dtype), tree)


def _sq_norm_host(tree) -> float:
    """Squared global norm of an already-hosted numpy tree — no extra
    device round-trips on the streaming critical path."""
    return float(sum(
        float(np.vdot(g.astype(np.float32), g.astype(np.float32)))
        for g in jax.tree_util.tree_leaves(tree)))


class StreamedAdamW:
    """Streaming train step with an exact optax
    `chain(clip_by_global_norm, adamw)` update (no weight-decay mask)."""

    def __init__(self, spec: StreamSpec, learning_rate: float = 1e-5,
                 b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0, clip_norm: float = 1.0,
                 lr_schedule: Optional[Callable[[int], float]] = None,
                 use_decay_mask: bool = False,
                 moments_dtype: Optional[Any] = None):
        self.spec = spec
        self.hparams = (b1, b2, eps, weight_decay)
        self.learning_rate = learning_rate
        self.lr_schedule = lr_schedule
        self.clip_norm = clip_norm
        self.count = 0
        # moments_dtype=None keeps the adam moments in each param's own
        # dtype with update math in that dtype — bit-parity with the
        # monolithic optax step (optax mu_dtype default). Setting e.g.
        # 'bfloat16' halves the host-resident moment memory (the term
        # that decides whether a 13B stream fits host RAM: fp32 m+v is
        # 104 GB, bf16 is 52 GB) while the update math runs in fp32.
        self.moments_dtype = None if moments_dtype is None else \
            jnp.dtype(moments_dtype)
        # host-resident master copies: params + adam moments per part
        self.parts = [_host(spec.bottom)] + \
            [_host(p) for p in spec.layers] + [_host(spec.top)]
        self.m = [_zeros_like_host(p, self.moments_dtype)
                  for p in self.parts]
        self.v = [_zeros_like_host(p, self.moments_dtype)
                  for p in self.parts]
        if use_decay_mask:
            # the recipe's no-decay grouping: biases/LayerNorm excluded
            # (model_utils.decay_mask_fn parity)
            from fengshen_tpu.models.model_utils import decay_mask_fn
            self.masks = [jax.tree_util.tree_map(
                np.float32, decay_mask_fn(p)) for p in self.parts]
        else:
            self.masks = [jax.tree_util.tree_map(
                lambda x: np.float32(1.0), p) for p in self.parts]
        self._jits: dict = {}

    # -- jitted programs (compiled once; shapes repeat across layers) ----
    def _fwd_bottom(self):
        if "fb" not in self._jits:
            self._jits["fb"] = jax.jit(self.spec.bottom_fn)
        return self._jits["fb"]

    def _fwd_layer(self):
        if "fl" not in self._jits:
            self._jits["fl"] = jax.jit(self.spec.layer_fn)
        return self._jits["fl"]

    def _grad_top(self):
        if "gt" not in self._jits:
            def run(p, h, batch, rng):
                def f(p, h):
                    return self.spec.top_fn(p, h, batch, rng)
                (loss, metrics), (gp, gh) = jax.value_and_grad(
                    f, argnums=(0, 1), has_aux=True)(p, h)
                return loss, metrics, gp, gh
            self._jits["gt"] = jax.jit(run)
        return self._jits["gt"]

    def _vjp_layer(self):
        if "vl" not in self._jits:
            def run(p, h, batch, rng, g_out):
                def f(p, h):
                    return self.spec.layer_fn(p, h, batch, rng)
                _, vjp = jax.vjp(f, p, h)
                gp, gh = vjp(g_out)
                return gp, gh
            self._jits["vl"] = jax.jit(run)
        return self._jits["vl"]

    def _vjp_bottom(self):
        if "vb" not in self._jits:
            def run(p, batch, rng, g_out):
                def f(p):
                    return self.spec.bottom_fn(p, batch, rng)
                _, vjp = jax.vjp(f, p)
                return vjp(g_out)[0]
            self._jits["vb"] = jax.jit(run)
        return self._jits["vb"]

    def _update(self):
        if "up" not in self._jits:
            b1, b2, eps, wd = self.hparams

            reduced = self.moments_dtype is not None

            def run(p, g, m, v, mask, scale, lr, count):
                def leaf(p, g, m, v, mask):
                    if reduced:
                        # reduced-precision moment STORAGE, fp32 math:
                        # bf16 accumulation would lose small updates
                        # (1 + x == 1 for x < 2^-8)
                        store_m, store_v = m.dtype, v.dtype
                        m, v = (m.astype(jnp.float32),
                                v.astype(jnp.float32))
                        g = (g * scale).astype(jnp.float32)
                    else:
                        # param-dtype math — bit-parity with optax
                        store_m = store_v = m.dtype
                        g = (g * scale).astype(m.dtype)
                    m2 = b1 * m + (1 - b1) * g
                    v2 = b2 * v + (1 - b2) * g * g
                    mhat = m2 / (1 - b1 ** count)
                    vhat = v2 / (1 - b2 ** count)
                    upd = mhat / (jnp.sqrt(vhat) + eps) + wd * mask * p
                    return ((p - lr * upd).astype(p.dtype),
                            m2.astype(store_m), v2.astype(store_v))
                out = jax.tree_util.tree_map(leaf, p, g, m, v, mask)
                new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                               is_leaf=lambda t:
                                               isinstance(t, tuple))
                new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                               is_leaf=lambda t:
                                               isinstance(t, tuple))
                new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                               is_leaf=lambda t:
                                               isinstance(t, tuple))
                return new_p, new_m, new_v
            self._jits["up"] = jax.jit(run, donate_argnums=(0, 1, 2, 3))
        return self._jits["up"]

    # -- the streamed step ----------------------------------------------
    def step(self, batch, rng=None):
        rng = jax.random.PRNGKey(0) if rng is None else rng
        n_layers = len(self.spec.layers)
        rngs = jax.random.split(rng, n_layers + 2)
        dev = jax.device_put

        # forward: boundaries[l] is the INPUT to layer l
        h = self._fwd_bottom()(dev(self.parts[0]), batch, rngs[0])
        boundaries = [h]
        for l in range(n_layers):
            h = self._fwd_layer()(dev(self.parts[1 + l]), h, batch,
                                  rngs[1 + l])
            if l < n_layers - 1:
                boundaries.append(h)

        loss, metrics, g_top, g_h = self._grad_top()(
            dev(self.parts[-1]), h, batch, rngs[-1])
        grads: list = [None] * (n_layers + 2)
        grads[-1] = _host(g_top)
        sq = _sq_norm_host(grads[-1])

        # backward: stream each layer a second time, recompute via vjp
        for l in reversed(range(n_layers)):
            g_l, g_h = self._vjp_layer()(
                dev(self.parts[1 + l]), boundaries[l], batch,
                rngs[1 + l], g_h)
            grads[1 + l] = _host(g_l)
            sq += _sq_norm_host(grads[1 + l])
        g_bot = self._vjp_bottom()(dev(self.parts[0]), batch, rngs[0],
                                   g_h)
        grads[0] = _host(g_bot)
        sq += _sq_norm_host(grads[0])

        # optax clip_by_global_norm: scale only when the norm exceeds
        global_norm = float(np.sqrt(sq))
        scale = 1.0 if (self.clip_norm is None or
                        global_norm <= self.clip_norm) else \
            self.clip_norm / max(global_norm, 1e-12)

        self.count += 1
        lr = self.lr_schedule(self.count) if self.lr_schedule else \
            self.learning_rate
        for i in range(len(self.parts)):
            p, m, v = self._update()(
                dev(self.parts[i]), dev(grads[i]), dev(self.m[i]),
                dev(self.v[i]), dev(self.masks[i]), jnp.float32(scale),
                jnp.float32(lr), jnp.int32(self.count))
            self.parts[i], self.m[i], self.v[i] = \
                _host(p), _host(m), _host(v)
            grads[i] = None  # free host grad as soon as it's consumed
        metrics = {k: float(vv) for k, vv in (metrics or {}).items()}
        metrics["grad_norm"] = global_norm
        return float(loss), metrics

    def params(self):
        """Joined params pytree (HOST numpy — transfers happen only when
        a consumer uses it) for eval/predict/checkpointing."""
        return self._join(self.parts[0],
                          self.parts[1:-1], self.parts[-1])

    def _join(self, bottom, layers, top):
        raise NotImplementedError  # installed by the spec factory


# -- family split: LLaMA causal LM ----------------------------------------

def llama_stream_spec(config, params,
                      deterministic: bool = True) -> StreamSpec:
    """Factor LlamaForCausalLM params into embed / decoder layers /
    (norm + lm_head + causal CE)."""
    from fengshen_tpu.models.llama.modeling_llama import LlamaDecoderLayer
    from fengshen_tpu.ops.norms import RMSNorm
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    model_p = params["model"]
    if config.scan_layers:
        stacked = model_p["layers"]["layer"]
        layers = [jax.tree_util.tree_map(lambda x: x[i], stacked)
                  for i in range(config.num_hidden_layers)]
    else:
        layers = [model_p[f"layers_{i}"]
                  for i in range(config.num_hidden_layers)]
    bottom = {"embed_tokens": model_p["embed_tokens"]}
    top = {"norm": model_p["norm"], "lm_head": params["lm_head"]}
    dt = jnp.dtype(config.dtype)

    def bottom_fn(p, batch, rng):
        table = p["embed_tokens"]["embedding"]
        return jnp.take(table, batch["input_ids"], axis=0).astype(dt)

    layer_mod = LlamaDecoderLayer(config)

    def layer_fn(p, h, batch, rng):
        return layer_mod.apply(
            {"params": p}, h, batch.get("attention_mask"),
            deterministic=deterministic)

    norm_mod = RMSNorm(epsilon=config.rms_norm_eps)

    def top_fn(p, h, batch, rng):
        h = norm_mod.apply({"params": p["norm"]}, h)
        logits = h @ p["lm_head"]["kernel"].astype(h.dtype)
        labels = batch.get("labels", batch["input_ids"])
        loss, n = stable_cross_entropy(logits[:, :-1], labels[:, 1:])
        return loss, {"n_tokens": n}

    spec = StreamSpec(bottom_fn, layer_fn, top_fn, bottom, layers, top)

    def join(bottom, layers, top):
        if config.scan_layers:
            # np.stack keeps the joined tree HOST-resident — a jnp join
            # would materialize the full model in HBM, defeating offload
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *layers)
            model = {"embed_tokens": bottom["embed_tokens"],
                     "layers": {"layer": stacked},
                     "norm": top["norm"]}
        else:
            model = {"embed_tokens": bottom["embed_tokens"],
                     "norm": top["norm"]}
            for i, l in enumerate(layers):
                model[f"layers_{i}"] = l
        return {"model": model, "lm_head": top["lm_head"]}

    spec.join = join
    return spec


# -- family split: classification TaskModel over MegatronBert -------------

def megatron_classifier_stream_spec(config, params, num_labels: int,
                                    deterministic: bool = True
                                    ) -> StreamSpec:
    """Factor the AFQMC TaskModel (erlangshen/MegatronBert backbone +
    cls_layer) for streaming — the mechanical 7 GB recipe
    (reference: demo_classification_afqmc_erlangshen_offload.sh:9-33).

    `deterministic=False` trains with the config's dropout, driven by
    the per-layer rng the engine threads through — the vjp recompute
    reuses the SAME rng, so forward and backward see identical masks."""
    from flax import linen as nn

    from fengshen_tpu.models.megatron_bert.modeling_megatron_bert import (
        LayerNorm, MegatronBertLayer)
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    enc = params["bert_encoder"]
    if config.scan_layers:
        stacked = enc["layer"]["block"]
        layers = [jax.tree_util.tree_map(lambda x: x[i], stacked)
                  for i in range(config.num_hidden_layers)]
    else:
        layers = [enc[f"layer_{i}"]
                  for i in range(config.num_hidden_layers)]
    bottom = {k: enc[k] for k in ("word_embeddings",
                                  "position_embeddings",
                                  "token_type_embeddings")}
    top = {"ln": enc["ln"], "pooler": enc["pooler"],
           "cls_layer": params["cls_layer"]}
    dt = jnp.dtype(config.dtype)

    def bottom_fn(p, batch, rng):
        ids = batch["input_ids"]
        seq = ids.shape[1]
        tok_type = batch.get("token_type_ids", jnp.zeros_like(ids))
        h = jnp.take(p["word_embeddings"]["embedding"], ids, axis=0) + \
            p["position_embeddings"]["embedding"][None, :seq] + \
            jnp.take(p["token_type_embeddings"]["embedding"], tok_type,
                     axis=0)
        h = h.astype(dt)
        if not deterministic:
            h = nn.Dropout(config.hidden_dropout_prob).apply(
                {}, h, deterministic=False, rngs={"dropout": rng})
        return h

    layer_mod = MegatronBertLayer(config)

    def layer_fn(p, h, batch, rng):
        return layer_mod.apply({"params": p}, h,
                               batch.get("attention_mask"),
                               deterministic=deterministic,
                               rngs=None if deterministic else
                               {"dropout": rng})

    ln_mod = LayerNorm(epsilon=config.layer_norm_eps)

    def top_fn(p, h, batch, rng):
        h = ln_mod.apply({"params": p["ln"]}, h)
        pooled = jnp.tanh(
            h[:, 0] @ p["pooler"]["kernel"].astype(h.dtype) +
            p["pooler"]["bias"].astype(h.dtype))
        logits = pooled @ p["cls_layer"]["kernel"].astype(h.dtype) + \
            p["cls_layer"]["bias"].astype(h.dtype)
        labels = batch["labels"]
        loss, _ = stable_cross_entropy(logits[:, None, :],
                                       labels[:, None])
        acc = jnp.mean(logits.argmax(-1) == labels)
        return loss, {"acc": acc}

    spec = StreamSpec(bottom_fn, layer_fn, top_fn, bottom, layers, top)

    def join(bottom, layers, top):
        if config.scan_layers:
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *layers)
            enc = {**bottom, "layer": {"block": stacked},
                   "ln": top["ln"], "pooler": top["pooler"]}
        else:
            enc = {**bottom, "ln": top["ln"], "pooler": top["pooler"]}
            for i, l in enumerate(layers):
                enc[f"layer_{i}"] = l
        return {"bert_encoder": enc, "cls_layer": top["cls_layer"]}

    spec.join = join
    return spec


def make_streamed(spec: StreamSpec, **kw) -> StreamedAdamW:
    eng = StreamedAdamW(spec, **kw)
    eng._join = lambda b, ls, t: spec.join(b, ls, t)
    return eng


def run_streamed_fit(args, spec: StreamSpec, loader, apply_fn,
                     ckpt=None, log=None, park_on_device=False):
    """The shared streamed training loop (reference recipe parity:
    configured scheduler, adam betas/eps, no-decay mask, global-norm
    clip): drives `StreamedAdamW` over `loader`, fires the checkpoint
    callbacks, and returns a TrainState whose params are parked on
    device once for the predict path."""
    import optax

    from fengshen_tpu.models.model_utils import (get_scheduler,
                                                 get_total_steps)
    from fengshen_tpu.trainer.train_state import TrainState
    from fengshen_tpu.utils.utils import report_memory

    total_steps = get_total_steps(args, len(loader.dataset),
                                  args.train_batchsize)
    schedule = get_scheduler(args, total_steps)
    # the streamed loop IS the "stream" rung of the offload ladder
    # (docs/offload.md): resolve the policy so the placement + its
    # reason get the same loud announcement as the Trainer levels, and
    # so moments_dtype becomes a policy knob — "param" (the default)
    # demands bit-parity storage and is never auto-upgraded; "auto"
    # lets the policy pick bfloat16 storage when fp32 moments would
    # dwarf host RAM (the sizing term that decides whether a 13B
    # stream fits the host); explicit dtypes pass through
    from fengshen_tpu.trainer.memory import (
        MOMENT_BYTES_PER_PARAM_FP32, resolve_offload_policy)
    leaves = jax.tree_util.tree_leaves(
        [spec.bottom, spec.layers, spec.top])
    n_params = sum(int(np.prod(np.shape(x))) for x in leaves)
    raw_moments = getattr(args, "offload_moments_dtype", "param")
    policy = resolve_offload_policy(
        "stream",
        params_bytes=sum(int(getattr(x, "nbytes", 0)) for x in leaves),
        opt_bytes=n_params * MOMENT_BYTES_PER_PARAM_FP32,
        moments_dtype=(None if raw_moments == "auto" else raw_moments))
    eng = make_streamed(
        spec,
        # optax schedules are 0-based; the engine count is 1-based
        lr_schedule=lambda count: float(schedule(count - 1)),
        b1=getattr(args, "adam_beta1", 0.9),
        b2=getattr(args, "adam_beta2", 0.999),
        eps=getattr(args, "adam_epsilon", 1e-8),
        weight_decay=getattr(args, "weight_decay", 0.01),
        clip_norm=getattr(args, "gradient_clip_val", 0.0) or None,
        use_decay_mask=True,
        moments_dtype=policy.moments_dtype)

    class _TrainerView:
        global_step = 0
        consumed_samples = 0

    view = _TrainerView()

    def _state():
        return TrainState.create(apply_fn=apply_fn, params=eng.params(),
                                 tx=optax.set_to_zero())

    raw_max = getattr(args, "max_steps", 0) or 0
    max_steps = raw_max if raw_max > 0 else total_steps
    max_epochs = getattr(args, "max_epochs", None) or 1
    step = 0
    rng = jax.random.PRNGKey(getattr(args, "seed", 42))
    for _epoch in range(max_epochs):
        for batch in loader:
            batch = {k: jnp.asarray(v) for k, v in batch.items()
                     if k != "id"}
            rng, step_rng = jax.random.split(rng)
            loss, metrics = eng.step(batch, step_rng)
            step += 1
            view.global_step = step
            view.consumed_samples = step * args.train_batchsize
            if log is not None and step % max(
                    getattr(args, "log_every_n_steps", 1), 1) == 0:
                mem = report_memory("streamed")
                peak = max((d["peak_bytes_in_use"]
                            for d in mem.values()), default=0)
                log(step, loss, metrics, peak)
            if ckpt is not None and ckpt.every_n_train_steps and \
                    step % ckpt.every_n_train_steps == 0:
                # join the host parts only when a save actually fires
                ckpt.on_train_step_end(view, _state())
            if step >= max_steps:
                break
        if step >= max_steps:
            break
    final = _state()
    if ckpt is not None:
        ckpt.on_fit_end(view, final)
    if park_on_device:
        # predict dispatches per batch; park the joined tree on device
        # ONCE. Callers whose model dwarfs HBM (the 13B streamed
        # finetune) must NOT ask for this — the host-resident tree is
        # the point.
        return final.replace(params=jax.device_put(final.params))
    return final
