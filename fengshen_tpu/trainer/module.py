"""TrainModule — the LightningModule-equivalent contract.

The reference's doctrine (reference: fengshen/README.md:70-78) is that every
workload is a LightningDataModule + LightningModule + callbacks. The
TPU-native contract keeps the same shape but is functional: the module owns
the flax model, the loss, the partition rules, and the optimizer config; the
Trainer owns jit, sharding, the step loop, checkpointing and logging.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from fengshen_tpu.models import model_utils


class TrainModule:
    """Subclass and implement `init_params` and `training_loss`.

    Mapping from the reference's LightningModule methods
    (e.g. fengshen/examples/ziya_llama/finetune_ziya_llama.py:98-182):
    - ``setup`` → ``setup`` (called once before fit)
    - ``training_step`` → ``training_loss`` (pure: params, batch, rng →
      (loss, metrics))
    - ``validation_step`` → ``validation_loss``
    - ``configure_optimizers`` → ``configure_optimizers`` (optax)
    - checkpoint hooks → trainer-managed (orbax)
    """

    def __init__(self, args: Any):
        self.args = args

    # -- model -----------------------------------------------------------
    def setup(self, stage: str = "fit") -> None:
        """Build/load the model; reference loads per-TP-rank HF shards here
        (finetune_ziya_llama.py:102-107) — we load once, resharded on
        device_put."""

    def init_params(self, rng: jax.Array) -> Any:
        raise NotImplementedError

    # -- losses ----------------------------------------------------------
    def training_loss(self, params: Any, batch: Any, rng: jax.Array
                      ) -> tuple[jax.Array, dict]:
        raise NotImplementedError

    def validation_loss(self, params: Any, batch: Any, rng: jax.Array
                        ) -> tuple[jax.Array, dict]:
        return self.training_loss(params, batch, rng)

    # -- parallelism -----------------------------------------------------
    def partition_rules(self) -> list[tuple[str, P]]:
        """Default: replicate everything (pure data parallel)."""
        return [(".*", P(None))]

    def batch_spec(self, batch: Any) -> Any:
        """PartitionSpec pytree for a batch; default shards dim0 over the
        batch axes."""
        from fengshen_tpu.parallel.partition import shard_batch_spec
        return jax.tree_util.tree_map(
            lambda x: shard_batch_spec(np.ndim(x)), batch)

    # -- optimization ----------------------------------------------------
    def configure_optimizers(self, total_steps: int, params: Any = None):
        return model_utils.configure_optimizers(self.args, total_steps,
                                                params)

    # -- accounting ------------------------------------------------------
    def flops_per_token(self) -> Optional[float]:
        """Forward+backward FLOPs per token (6·N for dense decoders); used
        for the MFU metric the reference never measured (SURVEY.md §5.1)."""
        return None

    def tokens_in_batch(self, batch: Any) -> int:
        for key in ("input_ids", "tokens"):
            if isinstance(batch, dict) and key in batch:
                return int(np.prod(np.shape(batch[key])))
        leaves = jax.tree_util.tree_leaves(batch)
        return int(np.prod(np.shape(leaves[0]))) if leaves else 0
