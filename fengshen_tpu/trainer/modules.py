"""Reusable TrainModules for common objectives."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


class CausalLMModule(TrainModule):
    """Causal-LM training: shift-by-one CE with -100 label masking.

    The objective of the reference's GPT2/LLaMA workloads
    (reference: fengshen/examples/ziya_llama/finetune_ziya_llama.py:133-148,
    loss at fengshen/models/llama/modeling_llama.py:334-339). The logits→loss
    path uses vocab-parallel CE so TP never all-gathers the [B,S,V] logits.
    """

    def __init__(self, args: Any, model, config):
        super().__init__(args)
        self.model = model
        self.config = config

    def init_params(self, rng):
        seq = min(getattr(self.args, "max_seq_length", 32), 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        labels = batch.get("labels", batch["input_ids"])
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch.get("attention_mask"),
            deterministic=False)
        shifted_logits = logits[:, :-1]
        shifted_labels = labels[:, 1:]
        loss, n_tokens = vocab_parallel_cross_entropy(
            shifted_logits, shifted_labels)
        acc = (shifted_logits.argmax(-1) == shifted_labels)
        valid = shifted_labels != -100
        acc = (acc * valid).sum() / jnp.maximum(valid.sum(), 1)
        return loss, {"acc": acc, "n_tokens": n_tokens}

    def partition_rules(self):
        if hasattr(self.model, "partition_rules"):
            return self.model.partition_rules()
        return super().partition_rules()

    def flops_per_token(self) -> Optional[float]:
        cfg = self.config
        if hasattr(cfg, "hidden_size") and hasattr(cfg, "num_hidden_layers"):
            h, l = cfg.hidden_size, cfg.num_hidden_layers
            inter = getattr(cfg, "intermediate_size", 4 * h) or 4 * h
            v = getattr(cfg, "vocab_size", 0)
            per_layer = 4 * h * h + 2 * h * inter + h * inter
            return 6.0 * (l * per_layer + h * v)
        return None
