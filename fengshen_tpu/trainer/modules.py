"""Reusable TrainModules for common objectives."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


class CausalLMModule(TrainModule):
    """Causal-LM training: shift-by-one CE with -100 label masking.

    The objective of the reference's GPT2/LLaMA workloads
    (reference: fengshen/examples/ziya_llama/finetune_ziya_llama.py:133-148,
    loss at fengshen/models/llama/modeling_llama.py:334-339). The logits→loss
    path uses vocab-parallel CE so TP never all-gathers the [B,S,V] logits.
    """

    def __init__(self, args: Any, model, config):
        super().__init__(args)
        self.model = model
        self.config = config

    def init_params(self, rng):
        seq = min(getattr(self.args, "max_seq_length", 32), 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def _fused_ce_active(self) -> bool:
        """Chunked fused head+CE is a replicated-head lever; under
        tensor parallelism vocab-parallel CE already avoids the full
        logits tensor, so the fused path stays off there."""
        from fengshen_tpu.parallel.mesh import get_mesh
        chunks = getattr(self.config, "fused_ce_chunks", 0)
        if not chunks:
            return False
        mesh = get_mesh()
        return mesh is None or mesh.shape.get("tensor", 1) == 1

    def _fused_ce_mode(self) -> str:
        """Which fused-head path training_loss takes: ``"off"`` (no
        fused_ce_chunks — plain logits + vocab-parallel CE),
        ``"replicated"`` (the chunked scan over a replicated head, via
        the ops.pallas fused_ce dispatch seam), or ``"vocab_parallel"``
        (tensor-parallel head: the chunked fused CE runs INSIDE the
        vocab shard_map, so neither the full nor the sharded [B, S, V]
        logits ever materialize — docs/kernels.md)."""
        chunks = getattr(self.config, "fused_ce_chunks", 0)
        if not chunks:
            return "off"
        return "replicated" if self._fused_ce_active() else \
            "vocab_parallel"

    def _lm_head_kernel(self, params):
        """[H, V] head weight for the fused path. Models may publish
        their own lookup (GPT2's wte-tied head); the default covers the
        llama layout (tied embedding or lm_head Dense)."""
        hook = getattr(type(self.model), "lm_head_kernel", None)
        if hook is not None:
            return hook(params)
        if getattr(self.config, "tie_word_embeddings", False):
            return params["model"]["embed_tokens"]["embedding"].T
        return params["lm_head"]["kernel"]

    def training_loss(self, params, batch, rng):
        labels = batch.get("labels", batch["input_ids"])
        extra = {}
        if "position_ids" in batch:  # packed rows restart positions
            extra["position_ids"] = batch["position_ids"]
        mode = self._fused_ce_mode()
        if mode != "off":
            hidden, mutated = self.model.apply(
                {"params": params}, batch["input_ids"],
                attention_mask=batch.get("attention_mask"),
                deterministic=False, mutable=["losses"],
                rngs={"dropout": rng}, return_hidden=True, **extra)
            kernel = self._lm_head_kernel(params).astype(hidden.dtype)
            if mode == "vocab_parallel":
                from fengshen_tpu.parallel.cross_entropy import (
                    fused_vocab_parallel_ce)
                loss, n_tokens, n_correct = fused_vocab_parallel_ce(
                    hidden[:, :-1], kernel, labels[:, 1:],
                    num_chunks=self.config.fused_ce_chunks)
            else:
                from fengshen_tpu.ops.fused_ce import causal_fused_loss
                loss, n_tokens, n_correct = causal_fused_loss(
                    hidden, kernel, labels,
                    num_chunks=self.config.fused_ce_chunks)
            metrics = {"acc": n_correct / jnp.maximum(n_tokens, 1),
                       "n_tokens": n_tokens}
            aux_leaves = jax.tree_util.tree_leaves(
                mutated.get("losses", {}))
            if aux_leaves:
                aux = sum(jnp.sum(leaf) for leaf in aux_leaves)
                loss = loss + getattr(self.config, "moe_aux_weight",
                                      0.01) * aux
                metrics["aux_loss"] = aux
            return loss, metrics
        logits, mutated = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch.get("attention_mask"),
            deterministic=False, mutable=["losses"],
            rngs={"dropout": rng}, **extra)
        shifted_logits = logits[:, :-1]
        shifted_labels = labels[:, 1:]
        loss, n_tokens = vocab_parallel_cross_entropy(
            shifted_logits, shifted_labels)
        metrics = {}
        # auxiliary losses sowed by nested layers (e.g. the SwitchMoE
        # load-balance term under ("losses","moe_aux_loss"))
        aux_leaves = jax.tree_util.tree_leaves(mutated.get("losses", {}))
        if aux_leaves:
            aux = sum(jnp.sum(leaf) for leaf in aux_leaves)
            weight = getattr(self.config, "moe_aux_weight", 0.01)
            loss = loss + weight * aux
            metrics["aux_loss"] = aux
        acc = (shifted_logits.argmax(-1) == shifted_labels)
        valid = shifted_labels != -100
        acc = (acc * valid).sum() / jnp.maximum(valid.sum(), 1)
        metrics.update({"acc": acc, "n_tokens": n_tokens})
        return loss, metrics

    def partition_rules(self):
        if hasattr(self.model, "partition_rules"):
            return self.model.partition_rules()
        return super().partition_rules()

    def flops_per_token(self) -> Optional[float]:
        # the single estimator (docs/observability.md): same numbers as
        # the old inline formula for full-kv models, GQA-aware beyond it
        from fengshen_tpu.observability import estimate_flops_per_token
        return estimate_flops_per_token(self.config)


class PipelinedCausalLMModule(TrainModule):
    """Causal-LM training with the decoder stack run as a GPipe pipeline
    over the 'pipe' mesh axis (VERDICT r1 item 8: pipeline parallelism
    integrated with the Trainer; the reference's pipeline topology exists
    but is never wired into training, reference:
    fengshen/strategies/megatron_deepspeed.py:347-361).

    Parameter layout: decoder layers are stacked [n_stages, layers_per_
    stage, ...] and sharded P('pipe') on the stage dim; embedding/norm/head
    are replicated across the pipe axis and differentiated by plain
    autodiff around the pipeline.
    """

    def __init__(self, args, config, n_microbatches: int = 0):
        super().__init__(args)
        from fengshen_tpu.models.llama.modeling_llama import (
            LlamaDecoderLayer)
        from fengshen_tpu.ops.norms import RMSNorm
        from flax import linen as nn

        from fengshen_tpu.ops.embedding import VocabParallelEmbed

        self.config = config
        self.layer_mod = LlamaDecoderLayer(config)
        self.embed_mod = VocabParallelEmbed(
            config.vocab_size, config.hidden_size,
            embedding_init=nn.initializers.normal(
                config.initializer_range))
        self.norm_mod = RMSNorm(epsilon=config.rms_norm_eps)
        self.n_microbatches = n_microbatches or None

    def _mesh_stages(self):
        from fengshen_tpu.parallel.mesh import get_mesh
        mesh = get_mesh()
        return mesh, int(mesh.shape.get("pipe", 1))

    def init_params(self, rng):
        cfg = self.config
        _, n_stages = self._mesh_stages()
        assert cfg.num_hidden_layers % n_stages == 0, \
            "num_hidden_layers must divide evenly into pipeline stages"
        per_stage = cfg.num_hidden_layers // n_stages
        seq = min(getattr(self.args, "max_seq_length", 32), 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        h = jnp.zeros((1, seq, cfg.hidden_size), jnp.float32)

        r_embed, r_layers, r_norm = jax.random.split(rng, 3)
        layer_rngs = jax.random.split(
            r_layers, cfg.num_hidden_layers).reshape(
                n_stages, per_stage, -1)
        layer_params = jax.vmap(jax.vmap(
            lambda k: self.layer_mod.init(k, h)["params"]))(layer_rngs)
        return {
            "embed": self.embed_mod.init(r_embed, ids)["params"],
            "layers": layer_params,
            "norm": self.norm_mod.init(r_norm, h)["params"],
        }

    def _stage_fn(self, stage_params, h):
        def body(carry, lp):
            return self.layer_mod.apply({"params": lp}, carry), None

        out, _ = jax.lax.scan(body, h, stage_params)
        return out

    def training_loss(self, params, batch, rng):
        from fengshen_tpu.parallel.pipeline import pipeline_apply
        mesh, n_stages = self._mesh_stages()
        ids = batch["input_ids"]
        labels = batch.get("labels", ids)
        batch_size = ids.shape[0]
        n_micro = self.n_microbatches or max(n_stages, 1)
        assert batch_size % n_micro == 0, \
            f"batch {batch_size} not divisible into {n_micro} microbatches"

        h = self.embed_mod.apply({"params": params["embed"]}, ids)
        micro = h.reshape((n_micro, batch_size // n_micro) + h.shape[1:])
        out = pipeline_apply(self._stage_fn, params["layers"], micro,
                             mesh=mesh, axis_name="pipe")
        h = out.reshape(h.shape)
        h = self.norm_mod.apply({"params": params["norm"]}, h)
        embedding = params["embed"]["embedding"]
        logits = h @ embedding.T.astype(h.dtype)
        loss, n_tokens = vocab_parallel_cross_entropy(logits[:, :-1],
                                                      labels[:, 1:])
        return loss, {"n_tokens": n_tokens}

    def partition_rules(self):
        # stage dim over 'pipe'; within a stage the stacked layer kernels
        # [stage, per_stage, in, out] keep the Megatron column/row layout
        # over fsdp/tensor (pipe composes with tp/fsdp in one SPMD program,
        # mirroring the reference's pipe-outer/model-inner topology,
        # reference: fengshen/strategies/megatron_deepspeed.py:347-354)
        return [
            (r"embed/embedding", P("tensor", "fsdp")),
            (r"layers/.*(q_proj|k_proj|v_proj|gate_proj|up_proj)/kernel",
             P("pipe", None, "fsdp", "tensor")),
            (r"layers/.*(o_proj|down_proj)/kernel",
             P("pipe", None, "tensor", "fsdp")),
            (r"layers/.*", P("pipe")),
            (r".*", P(None)),
        ]

    def flops_per_token(self):
        from fengshen_tpu.observability import estimate_flops_per_token
        return estimate_flops_per_token(self.config)


class LoraTrainModule(TrainModule):
    """Wrap ANY TrainModule for LoRA finetuning (reference's roadmap
    item, ziya_llama/README.md:59; merge tool fs_merge_weight.py).

    params become the two-tree {'base': inner params, 'lora': adapters}
    (`ops/lora.py`): the loss runs the inner module over
    `apply_lora(base, lora)` — merged INSIDE the jitted step, no model
    changes — and the optimizer is a multi_transform that trains only
    lora_a/lora_b (base and the stored scales get set_to_zero, and
    adam moments exist only for the adapters — the memory win).
    Checkpoints carry the two-tree; `python -m fengshen_tpu.ops.lora`
    merges one into a plain servable checkpoint.
    """

    def __init__(self, inner: TrainModule, rank: int,
                 alpha: Optional[float] = None,
                 target_regex: str =
                 r"(q_proj|k_proj|v_proj|o_proj)",
                 train_regex: Optional[str] = None):
        super().__init__(inner.args)
        self.inner = inner
        self.rank, self.alpha, self.target_regex = rank, alpha, \
            target_regex
        # modules_to_save analog: base paths matching this regex train
        # FULLY (task heads are random init — frozen they would leave
        # logits a fixed random projection)
        self.train_regex = train_regex
        # the inner's model/config stay reachable for trainer hooks,
        # and the jit_predict opt-in carries through (without it the
        # predict path runs eagerly, re-materializing the merged base
        # tree per batch instead of letting XLA fuse the adapters into
        # the consumer matmuls)
        self.model = getattr(inner, "model", None)
        self.config = getattr(inner, "config", None)
        self.jit_predict = getattr(inner, "jit_predict", False)

    def setup(self, stage: str = "fit") -> None:
        self.inner.setup(stage)

    def init_params(self, rng):
        from fengshen_tpu.ops.lora import init_lora, train_path_matches
        base = self.inner.init_params(rng)
        lora = init_lora(base, jax.random.fold_in(rng, 1), self.rank,
                         self.target_regex, alpha=self.alpha)
        if self.train_regex and not any(
                train_path_matches(p, self.train_regex) for p, _ in
                jax.tree_util.tree_flatten_with_path(base)[0]):
            # a typo'd head regex would silently leave a random-init
            # head frozen — chance-level logits with no error signal
            raise ValueError(
                f"lora train_regex {self.train_regex!r} matches no "
                "base parameter (--lora_train_modules typo?)")
        return {"base": base, "lora": lora}

    def _merged(self, params):
        from fengshen_tpu.ops.lora import apply_lora, train_path_matches
        # stop_gradient on the frozen base: XLA then dead-code-
        # eliminates the full-size base weight-grad computation (the
        # LoRA memory/compute win — without it a full grad tree is
        # materialized and merely discarded by the optimizer mask) and
        # the logged grad_norm reflects the params actually training.
        # Leaves matching train_regex (fully-trained heads) must NOT be
        # stopped or their adamw updates would receive zero gradients —
        # the shared train_path_matches predicate keeps this in exact
        # agreement with the optimizer labels.
        base = jax.tree_util.tree_map_with_path(
            lambda path, leaf: leaf
            if train_path_matches(path, self.train_regex)
            else jax.lax.stop_gradient(leaf), params["base"])
        return apply_lora(base, params["lora"])

    def training_loss(self, params, batch, rng):
        return self.inner.training_loss(self._merged(params), batch, rng)

    def validation_loss(self, params, batch, rng):
        return self.inner.validation_loss(self._merged(params), batch,
                                          rng)

    def configure_optimizers(self, total_steps: int, params=None):
        import optax

        from fengshen_tpu.models import model_utils
        from fengshen_tpu.ops.lora import lora_param_labels

        from functools import partial

        # the standard factory WITH the no-decay mask (built over the
        # two-tree, so train_regex head biases/LayerNorms keep their
        # full-finetune no-decay treatment; adapter matrices decay)
        tx, schedule = model_utils.configure_optimizers(
            self.args, total_steps, params=params)
        tx = optax.multi_transform(
            {"lora": tx, "freeze": optax.set_to_zero()},
            partial(lora_param_labels, train_regex=self.train_regex))
        return tx, schedule

    def predict_step(self, params, batch, *args, **kw):
        hook = getattr(self.inner, "predict_step", None)
        if hook is None:
            raise AttributeError(
                f"{type(self.inner).__name__} defines no predict_step")
        return hook(self._merged(params), batch, *args, **kw)

    def partition_rules(self):
        # inner rules still re.search-match under the 'base/' prefix;
        # adapters fall to the catch-all (replicated — they're small)
        return self.inner.partition_rules()

    def batch_spec(self, batch):
        return self.inner.batch_spec(batch)

    def flops_per_token(self):
        return self.inner.flops_per_token()

    def tokens_in_batch(self, batch):
        return self.inner.tokens_in_batch(batch)


def add_lora_args(parser, targets_default: str,
                  train_default: "Optional[str]" = None):
    """The shared --lora_* flag block (family-specific defaults)."""
    parser.add_argument(
        "--lora_rank", default=0, type=int,
        help="LoRA finetuning: freeze the base model and train rank-r "
             "adapters (merge back with `python -m "
             "fengshen_tpu.ops.lora`). 0 = full finetune")
    parser.add_argument("--lora_alpha", default=None, type=float,
                        help="LoRA scale numerator (default 2*rank)")
    parser.add_argument(
        "--lora_targets", default=targets_default, type=str,
        help="regex over param paths selecting the kernels that get "
             "adapters")
    parser.add_argument(
        "--lora_train_modules", default=train_default, type=str,
        help="regex of base modules to train FULLY alongside the "
             "adapters (modules_to_save analog — task heads are "
             "random init and must not freeze)")
    return parser


def maybe_wrap_lora(module: TrainModule, args) -> TrainModule:
    """Wrap `module` in LoraTrainModule when --lora_rank is set (the
    shared driver wiring, incl. the offload_params conflict guard)."""
    if not getattr(args, "lora_rank", 0):
        return module
    if getattr(args, "offload_params", False):
        raise ValueError("--lora_rank already shrinks optimizer state "
                         "to the adapters; combine with "
                         "--offload_optimizer if needed, not "
                         "--offload_params")
    return LoraTrainModule(module, rank=args.lora_rank,
                           alpha=getattr(args, "lora_alpha", None),
                           target_regex=args.lora_targets,
                           train_regex=getattr(args,
                                               "lora_train_modules",
                                               None))
