"""Memory-placement subsystem: capability probe + offload policy.

The reference's headline capability is 10B-scale training on small
device footprints via DeepSpeed optimizer/param offload (the "1.3B
finetune in 7 GB" recipe, reference: fengshen/examples/classification/
demo_classification_afqmc_erlangshen_offload.sh). ZeRO-Offload (arxiv
2101.06840) and ZeRO-Infinity (arxiv 2104.07857) show host-memory
placement of optimizer state and master weights buys 10-100x larger
models per chip — but only when the runtime actually HAS the memory
kind the placement asks for. `with_memory_kind("pinned_host")` raises
at sharding construction on backends without that space (this repo's
CPU tier-1 backend exposes only `unpinned_host`), which is exactly how
the offload bench rungs died from seed through PR 8.

Two pieces fix that for good (docs/offload.md):

- **`probe_memory_capabilities()`** — detects, once per process, which
  memory kinds (`pinned_host` / `unpinned_host`) the live backend
  supports by attempting a sharding construction + a tiny transfer,
  plus the device/host byte budgets when the runtime reports them.
  The probe is plain host code between jit boundaries — it never runs
  inside a traced program (gated by the fslint clean-fixture test).
- **`OffloadPolicy`** — given the probe, the model's byte footprint
  (from `jax.eval_shape`, so no buffers are materialised), and the
  `--offload` flag, decides WHERE optimizer moments, master/param
  copies, and streamed parameters live. Levels form a ladder

      none -> opt -> opt_master -> stream

  and every level degrades gracefully DOWN the ladder when the memory
  kind it needs is unsupported, with one loud log line stating the
  chosen placement and why. `--offload_memory_kind` overrides the
  probe's host-kind choice; forcing an unsupported kind raises instead
  of silently degrading (an explicit override is a statement of fact
  about the hardware — being wrong about it must be loud).

The resolved policy feeds the TrainState shardings
(`create_sharded_state` / `offload_opt_state_shardings`), the
offloaded two-program step (`Trainer._build_offloaded_train_step`),
the streamed engine's `moments_dtype` knob (`StreamedAdamW`), the AOT
cache key + trusted-replay fingerprint (`OffloadPolicy.fingerprint` —
placement changes the compiled programs, so a stale cross-placement
cache hit is structurally impossible), and the observability gauges
(`fstpu_offload_level`, `fstpu_memory_kind_supported{kind}`,
`fstpu_offload_host_bytes`).
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Callable, Dict, Optional

#: the offload ladder, least to most aggressive; index = the numeric
#: value of the `fstpu_offload_level` gauge
OFFLOAD_LEVELS = ("none", "opt", "opt_master", "stream")

#: host memory kinds worth probing, preference order: pinned host
#: memory DMA-streams to the accelerator without a bounce buffer, so
#: it wins whenever the backend has it
HOST_MEMORY_KINDS = ("pinned_host", "unpinned_host")

#: fraction of the reported device budget the placement math may plan
#: against — the rest is headroom for activations/fragmentation
DEVICE_BUDGET_FRACTION = 0.9

#: fp32 adam moments (m + v) cost 8 bytes/param — the term that
#: decides whether a host-resident optimizer fits host RAM
#: (docs/offload.md has the sizing table)
MOMENT_BYTES_PER_PARAM_FP32 = 8


@dataclasses.dataclass(frozen=True)
class MemoryCapabilities:
    """What the live backend can actually place where."""

    backend: str
    device_count: int
    #: kind -> probed support (sharding construction + tiny transfer)
    supported: Dict[str, bool]
    #: the device's DEFAULT memory kind ("device" on TPU/GPU,
    #: "unpinned_host" on the CPU backend) — the safe target for
    #: "bring it back on-device" shardings; `with_memory_kind("device")`
    #: raises on backends whose default space has another name
    device_memory_kind: str
    #: per-device byte budget (memory_stats()["bytes_limit"]); None
    #: when the runtime does not report one (CPU backend)
    device_bytes: Optional[int]
    #: host RAM (sysconf); None when unavailable
    host_bytes: Optional[int]

    def supports(self, kind: str) -> bool:
        return bool(self.supported.get(kind, False))

    @property
    def host_kind(self) -> Optional[str]:
        """Preferred host memory kind, or None when the backend has no
        addressable host space distinct from probing failures."""
        for kind in HOST_MEMORY_KINDS:
            if self.supports(kind):
                return kind
        return None

    def describe(self) -> dict:
        return {
            "backend": self.backend,
            "device_count": self.device_count,
            "supported": dict(sorted(self.supported.items())),
            "device_memory_kind": self.device_memory_kind,
            "device_bytes": self.device_bytes,
            "host_bytes": self.host_bytes,
        }


def _kind_supported(kind: str, device: Any) -> bool:
    """One probe attempt: construct a sharding with `kind` and move 8
    bytes through it. Construction raising (how this jax build reports
    a missing memory space) and transfer failures both read as
    unsupported."""
    import jax
    import numpy as np

    try:
        sharding = jax.sharding.SingleDeviceSharding(device,
                                                     memory_kind=kind)
        x = jax.device_put(np.ones((8,), np.uint8), sharding)
        jax.block_until_ready(x)
        return True
    except Exception:  # noqa: BLE001 — any failure means "do not
        # place data there"; the probe exists to turn the crash into
        # a capability bit
        return False


def _host_ram_bytes() -> Optional[int]:
    import os
    try:
        return int(os.sysconf("SC_PAGE_SIZE") *
                   os.sysconf("SC_PHYS_PAGES"))
    except (ValueError, OSError, AttributeError):
        return None


def _device_budget_bytes(device: Any) -> Optional[int]:
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — absent stats = unknown budget
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    return int(limit) if limit else None


#: (backend, device_count) -> MemoryCapabilities; probing costs a few
#: tiny transfers, and every placement decision consults it
_PROBE_CACHE: Dict[tuple, MemoryCapabilities] = {}


def probe_memory_capabilities(refresh: bool = False) -> MemoryCapabilities:
    """Detect the live backend's memory kinds + byte budgets, cached
    per process (keyed by backend + device count so a test that swaps
    backends re-probes)."""
    import jax

    devices = jax.devices()
    cache_key = (jax.default_backend(), len(devices))
    if not refresh and cache_key in _PROBE_CACHE:
        return _PROBE_CACHE[cache_key]
    device = devices[0]
    try:
        default_kind = device.default_memory().kind
    except Exception:  # noqa: BLE001 — older runtimes lack the API;
        # "device" is the conventional default-space name there
        default_kind = "device"
    caps = MemoryCapabilities(
        backend=jax.default_backend(),
        device_count=len(devices),
        supported={kind: _kind_supported(kind, device)
                   for kind in HOST_MEMORY_KINDS},
        device_memory_kind=default_kind,
        device_bytes=_device_budget_bytes(device),
        host_bytes=_host_ram_bytes(),
    )
    _PROBE_CACHE[cache_key] = caps
    return caps


@dataclasses.dataclass(frozen=True)
class OffloadPolicy:
    """A resolved placement decision (see module docstring).

    `level` is what actually runs; `requested` is what the flag asked
    for — they differ exactly when the ladder degraded (unsupported
    memory kind, no host space, a trainer that cannot stream) and
    `reason` says why.
    """

    requested: str
    level: str
    #: host memory kind for the adam moments between steps; None when
    #: they stay on-device (level "none")
    opt_state_kind: Optional[str]
    #: host memory kind for master/param copies between steps; None
    #: below level "opt_master"
    master_kind: Optional[str]
    #: storage dtype for streamed adam moments (StreamedAdamW knob);
    #: None keeps param-dtype bit-parity with monolithic optax
    moments_dtype: Optional[str]
    reason: str
    caps: MemoryCapabilities

    @property
    def offloads_opt_state(self) -> bool:
        return self.opt_state_kind is not None

    @property
    def offloads_params(self) -> bool:
        return self.master_kind is not None

    @property
    def level_index(self) -> int:
        return OFFLOAD_LEVELS.index(self.level)

    def fingerprint(self) -> str:
        """Stable identity of this placement for the AOT cache key and
        the trusted-replay fingerprint: two placements must never share
        a compiled-executable cache entry (docs/aot_cache.md)."""
        kinds = ",".join(sorted(k for k, v in self.caps.supported.items()
                                if v))
        return (f"offload={self.level};opt={self.opt_state_kind};"
                f"master={self.master_kind};moments={self.moments_dtype};"
                f"kinds={kinds};dev={self.caps.device_memory_kind}")

    def describe(self) -> dict:
        return {
            "requested": self.requested,
            "level": self.level,
            "opt_state_kind": self.opt_state_kind,
            "master_kind": self.master_kind,
            "moments_dtype": self.moments_dtype,
            "reason": self.reason,
            "memory_kinds": dict(sorted(self.caps.supported.items())),
        }


def _tree_bytes(tree: Any) -> int:
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * \
            np.dtype(dtype).itemsize
    return total


def state_byte_footprint(abstract_state: Any) -> tuple[int, int]:
    """(params_bytes, opt_state_bytes) of a TrainState eval_shape —
    the placement math's inputs, computed without materialising a
    single buffer."""
    return (_tree_bytes(getattr(abstract_state, "params", None)),
            _tree_bytes(getattr(abstract_state, "opt_state", None)))


def offload_request_from_args(args: Any) -> str:
    """The `--offload` / legacy `--offload_optimizer` flag surface,
    reduced to one request string. An explicit `--offload` wins; the
    deprecated boolean maps to "opt" only when `--offload` kept its
    "auto" default."""
    request = str(getattr(args, "offload", "auto") or "auto")
    if request == "auto" and getattr(args, "offload_optimizer", False):
        return "opt"
    return request


def resolve_offload_policy(request: str = "auto", *,
                           params_bytes: Optional[int] = None,
                           opt_bytes: Optional[int] = None,
                           abstract_state: Any = None,
                           memory_kind: str = "auto",
                           moments_dtype: Optional[str] = None,
                           can_stream: bool = True,
                           state_shard_ways: Optional[int] = None,
                           caps: Optional[MemoryCapabilities] = None,
                           log: Optional[Callable[[dict], None]] = None
                           ) -> OffloadPolicy:
    """Turn a request (`auto|none|opt|opt_master|stream`) into a
    concrete placement against the probed capabilities.

    The auto heuristic plans against ``DEVICE_BUDGET_FRACTION`` of the
    reported per-device budget times ``state_shard_ways`` — the number
    of ways ONE replica of the training state is actually sharded
    (fsdp x tensor x pipe for the Trainer's mesh; data/sequence axes
    REPLICATE the state, so counting them would overestimate capacity
    by the DP factor and under-offload). Defaults to the device count
    (fully sharded) when the caller has no mesh. Grads are costed at
    one param-sized tree:

    - params + grads + moments fit -> none
    - params + grads fit           -> opt (given a host kind)
    - otherwise                    -> stream (the only level that
      bounds the PER-STEP peak; opt_master only lowers between-step
      residency, so auto picks it solely as the best effort when the
      entry point cannot stream)

    With no reported budget (the CPU backend) auto picks "none":
    nothing indicates pressure, and the non-offloaded step is the fast
    path. Explicit levels keep their placement when the kinds exist and
    fall DOWN the ladder loudly when they don't; `can_stream=False`
    (the standard Trainer, which has no per-layer stream spec) demotes
    "stream" to "opt_master".

    `moments_dtype`: None lets the policy auto-suggest bfloat16 moment
    storage for "stream" when fp32 moments would dwarf host RAM;
    "param" explicitly demands param-dtype storage (bit-parity with
    monolithic optax — never auto-upgraded); any other dtype string is
    passed through.
    """
    if caps is None:
        caps = probe_memory_capabilities()
    if request not in ("auto",) + OFFLOAD_LEVELS:
        raise ValueError(
            f"unknown offload request {request!r}; expected one of "
            f"{('auto',) + OFFLOAD_LEVELS}")
    if abstract_state is not None:
        sized = state_byte_footprint(abstract_state)
        params_bytes = sized[0] if params_bytes is None else params_bytes
        opt_bytes = sized[1] if opt_bytes is None else opt_bytes

    # the host kind every offloading level places into
    if memory_kind not in ("auto",) + HOST_MEMORY_KINDS:
        raise ValueError(
            f"unknown --offload_memory_kind {memory_kind!r}; expected "
            f"one of {('auto',) + HOST_MEMORY_KINDS}")
    if memory_kind != "auto":
        if not caps.supports(memory_kind):
            raise ValueError(
                f"--offload_memory_kind={memory_kind} forced, but the "
                f"{caps.backend} backend does not support it (probed "
                f"kinds: {caps.describe()['supported']}); drop the "
                "override to let the probe pick")
        host_kind = memory_kind
        kind_why = f"forced by --offload_memory_kind={memory_kind}"
    else:
        host_kind = caps.host_kind
        kind_why = f"probe picked {host_kind}" if host_kind else \
            "no host memory kind supported"

    level, reason = _resolve_level(request, caps, host_kind,
                                   params_bytes, opt_bytes, can_stream,
                                   state_shard_ways)
    if level not in ("none", "stream") and host_kind is None:
        # nothing to place jax shardings INTO: opt/opt_master collapse.
        # "stream" is exempt — the streamed engine parks state as host
        # numpy (trainer/param_streaming.py) and needs no jax memory
        # kind, so it keeps its level (and its moments_dtype knob)
        reason = (f"requested {request!r} but the {caps.backend} "
                  "backend supports no host memory kind — running "
                  "without offload")
        level = "none"

    if moments_dtype == "param":
        # EXPLICIT bit-parity demand: param-dtype storage, never
        # auto-upgraded (the streamed drivers' flag contract)
        resolved_moments = None
    else:
        resolved_moments = moments_dtype
        if level == "stream" and resolved_moments is None and \
                opt_bytes and caps.host_bytes and \
                opt_bytes > caps.host_bytes // 2:
            # fp32 m+v would eat more than half of host RAM: halve the
            # moment storage (update math stays fp32 in StreamedAdamW)
            resolved_moments = "bfloat16"
            reason += ("; moments_dtype=bfloat16 (fp32 moments "
                       f"{opt_bytes >> 30} GiB > half of host RAM)")

    policy = OffloadPolicy(
        requested=request, level=level,
        opt_state_kind=host_kind if level != "none" else None,
        master_kind=host_kind
        if level in ("opt_master", "stream") else None,
        moments_dtype=resolved_moments if level == "stream" else None,
        reason=f"{reason} ({kind_why})",
        caps=caps)
    _announce(policy, log)
    return policy


def _resolve_level(request: str, caps: MemoryCapabilities,
                   host_kind: Optional[str],
                   params_bytes: Optional[int],
                   opt_bytes: Optional[int],
                   can_stream: bool,
                   state_shard_ways: Optional[int] = None
                   ) -> tuple[str, str]:
    if request == "none":
        return "none", "offload disabled by flag"
    if request == "auto":
        if not params_bytes or caps.device_bytes is None:
            return "none", ("auto: no device byte budget reported — "
                            "assuming everything fits")
        ways = max(1, min(int(state_shard_ways or caps.device_count),
                          caps.device_count))
        budget = caps.device_bytes * ways * DEVICE_BUDGET_FRACTION
        opt = opt_bytes or 0
        grads = params_bytes  # one param-sized tree during the step
        if params_bytes + grads + opt <= budget:
            return "none", (
                f"auto: params+grads+moments "
                f"{(params_bytes + grads + opt) >> 20} MiB fit the "
                f"{int(budget) >> 20} MiB device budget "
                f"({ways}-way sharded state)")
        if params_bytes + grads <= budget:
            # only the moments overflow the budget
            if host_kind is not None:
                return "opt", (
                    f"auto: moments ({opt >> 20} MiB) overflow the "
                    "device budget — parking them in host memory")
            # no jax host kind to park them in: "opt" cannot help;
            # streaming (host numpy) still can
            if can_stream:
                return "stream", (
                    f"auto: moments ({opt >> 20} MiB) overflow the "
                    "device budget and the backend has no host memory "
                    "kind for level 'opt' — per-layer streaming "
                    "instead")
            return "none", (
                f"auto: moments ({opt >> 20} MiB) overflow the device "
                "budget, but the backend has no host memory kind and "
                "this path cannot stream — running without offload "
                "(may OOM)")
        # past this point the PER-STEP peak (params+grads during the
        # gradient pass) overflows: opt_master only lowers BETWEEN-step
        # residency, not the peak, so auto never picks it as a fit —
        # per-layer streaming is the only level that bounds the peak
        if can_stream:
            return "stream", (
                f"auto: params+grads ({(params_bytes + grads) >> 20} "
                "MiB) overflow the device budget — per-layer streaming "
                "is the only level that bounds the per-step peak")
        return "opt_master", (
            f"auto: params+grads ({(params_bytes + grads) >> 20} MiB) "
            "overflow the device budget and this path cannot stream — "
            "opt_master is the deepest available level (best effort: "
            "between-step residency drops, but the per-step peak may "
            "still not fit)")
    if request == "stream" and not can_stream:
        return "opt_master", (
            "requested 'stream' but this entry point has no per-layer "
            "stream spec (use the --offload_params drivers, "
            "docs/offload.md) — degrading to opt_master")
    return request, f"explicit --offload={request}"


def _announce(policy: OffloadPolicy,
              log: Optional[Callable[[dict], None]]) -> None:
    """THE loud line: every resolved placement states itself and why,
    through the structured sink when one exists, stderr otherwise."""
    if log is not None:
        log({"event": "offload_policy", **policy.describe()})
        return
    print(f"[fengshen-tpu] offload policy: level={policy.level} "
          f"(requested={policy.requested}) "
          f"opt_state->{policy.opt_state_kind or 'device'} "
          f"master->{policy.master_kind or 'device'} — {policy.reason}",
          file=sys.stderr, flush=True)


def record_offload_metrics(policy: OffloadPolicy,
                           host_resident_bytes: Optional[int] = None,
                           registry: Any = None) -> None:
    """Export the placement to /metrics (docs/observability.md):
    `fstpu_offload_level` (ladder index), per-kind support bits, and
    the host-resident byte gauge. Host-side only — called once per fit,
    never from traced code."""
    from fengshen_tpu.observability import get_registry

    reg = registry if registry is not None else get_registry()
    reg.gauge("fstpu_offload_level",
              "resolved offload ladder level "
              "(0=none 1=opt 2=opt_master 3=stream)"
              ).set(float(policy.level_index))
    supported = reg.gauge("fstpu_memory_kind_supported",
                          "1 when the probed backend supports placing "
                          "data in this memory kind",
                          labelnames=("kind",))
    for kind in HOST_MEMORY_KINDS:
        supported.labels(kind).set(1.0 if policy.caps.supports(kind)
                                   else 0.0)
    if host_resident_bytes is not None:
        reg.gauge("fstpu_offload_host_bytes",
                  "bytes of training state parked in host memory "
                  "between steps").set(float(host_resident_bytes))
