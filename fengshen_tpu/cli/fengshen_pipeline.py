"""`fengshen-pipeline` console entry point.

Same CLI contract as the reference
(reference: fengshen/cli/fengshen_pipeline.py:7-30):

    fengshen-pipeline <task> <train|predict> --model ... --datasets ... [text]

The task name resolves to ``fengshen_tpu.pipelines.<task>.Pipeline``
dynamically, so adding a pipeline module automatically extends the CLI.
"""

from __future__ import annotations

import argparse
import importlib
import sys


def _resolve_pipeline(task: str):
    try:
        module = importlib.import_module(f"fengshen_tpu.pipelines.{task}")
    except ModuleNotFoundError as e:
        from fengshen_tpu import pipelines
        available = getattr(pipelines, "TASKS", [])
        raise SystemExit(
            f"unknown task {task!r} ({e}); available tasks: "
            f"{', '.join(available) or '(none registered)'}")
    if not hasattr(module, "Pipeline"):
        raise SystemExit(
            f"pipeline module fengshen_tpu.pipelines.{task} has no Pipeline")
    return module.Pipeline


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2:
        print("usage: fengshen-pipeline <task> <train|predict> "
              "[--model M] [--datasets D] [pipeline args...] [text]",
              file=sys.stderr)
        return 2
    task, mode, rest = argv[0], argv[1], argv[2:]
    if mode not in ("train", "predict"):
        print(f"unknown mode {mode!r}; expected train or predict",
              file=sys.stderr)
        return 2

    pipeline_cls = _resolve_pipeline(task)

    parser = argparse.ArgumentParser(prog=f"fengshen-pipeline {task} {mode}")
    parser.add_argument("--model", type=str, default=None)
    parser.add_argument("--datasets", type=str, default=None)
    parser.add_argument("text", nargs="*", default=[])
    if hasattr(pipeline_cls, "add_pipeline_specific_args"):
        parser = pipeline_cls.add_pipeline_specific_args(parser)
    args = parser.parse_args(rest)

    pipeline = pipeline_cls(args=args, model=args.model)
    if mode == "train":
        pipeline.train(args.datasets)
    else:
        for line in (args.text or sys.stdin):
            print(pipeline(line.strip()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
