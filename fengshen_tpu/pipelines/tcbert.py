"""Topic-classification (TCBert) pipeline
(reference: fengshen/pipelines/tcbert.py:40)."""

from fengshen_tpu.models.tcbert import TCBertPipelines as Pipeline

__all__ = ["Pipeline"]
