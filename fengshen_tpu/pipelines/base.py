"""Pipeline base constants and shared task-training scaffolding
(reference: fengshen/pipelines/base.py:1-2)."""

_CONFIG_MODEL_TYPE = "fengshen_model_type"
_CONFIG_TOKENIZER_TYPE = "fengshen_tokenizer_type"
