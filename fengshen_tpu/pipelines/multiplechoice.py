"""Multiple-choice (UniMC) pipeline
(reference: fengshen/pipelines/multiplechoice.py:41 — wraps the
self-contained UniMC package)."""

from fengshen_tpu.models.unimc import UniMCPipelines as Pipeline

__all__ = ["Pipeline"]
