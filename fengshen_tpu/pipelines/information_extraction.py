"""Information-extraction (UBERT) pipeline
(reference: fengshen/pipelines/information_extraction.py:27)."""

from fengshen_tpu.models.ubert import UbertPipelines as Pipeline

__all__ = ["Pipeline"]
