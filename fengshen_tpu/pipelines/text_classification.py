"""Text-classification pipeline.

Port of reference: fengshen/pipelines/text_classification.py:134-234 — a
pipeline object with `train()` (builds datamodule + task module + trainer)
and `__call__()` (tokenize → forward → softmax labels), model dispatch via
the config's `fengshen_model_type`
(reference: :25-31,158-164 `_model_dict`).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.megatron_bert import (
    MegatronBertConfig, MegatronBertForSequenceClassification)
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.trainer.module import TrainModule

#: fengshen_model_type → (config cls, model cls); grows as families land
_model_dict = {
    "huggingface-auto": (MegatronBertConfig,
                         MegatronBertForSequenceClassification),
    "megatron-bert": (MegatronBertConfig,
                      MegatronBertForSequenceClassification),
}


@dataclass
class _Collator:
    """Reference: pipelines/text_classification.py:38-91 _Collator."""

    tokenizer: Any
    max_length: int = 512
    texta_name: str = "sentence"
    textb_name: str = "sentence2"
    label_name: str = "label"

    def __call__(self, samples: list[dict]) -> dict:
        texta = [s[self.texta_name] for s in samples]
        textb = [s.get(self.textb_name) for s in samples]
        if any(b is None for b in textb):
            textb = None
        enc = self.tokenizer(texta, textb, padding="max_length",
                             truncation=True, max_length=self.max_length,
                             return_tensors="np")
        out = {"input_ids": enc["input_ids"].astype(np.int32),
               "attention_mask": enc["attention_mask"].astype(np.int32)}
        if "token_type_ids" in enc:
            out["token_type_ids"] = enc["token_type_ids"].astype(np.int32)
        if samples and self.label_name in samples[0]:
            out["labels"] = np.asarray(
                [int(s[self.label_name]) for s in samples], np.int32)
        return out


class _TaskModule(TrainModule):
    """Reference: pipelines/text_classification.py:38-91 _taskModel."""

    def __init__(self, args, model, config):
        super().__init__(args)
        self.model = model
        self.config = config

    def init_params(self, rng):
        ids = jnp.zeros((1, 16), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch.get("attention_mask"),
            token_type_ids=batch.get("token_type_ids"),
            deterministic=False, rngs={"dropout": rng})
        loss, _ = stable_cross_entropy(logits[:, None, :],
                                       batch["labels"][:, None])
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        return loss, {"acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


class TextClassificationPipeline:
    @staticmethod
    def add_pipeline_specific_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("text classification")
        parser.add_argument("--texta_name", default="sentence", type=str)
        parser.add_argument("--textb_name", default="sentence2", type=str)
        parser.add_argument("--label_name", default="label", type=str)
        parser.add_argument("--id_name", default="id", type=str)
        parser.add_argument("--max_length", default=512, type=int)
        parser.add_argument("--return_all_scores", action="store_true",
                            default=False)
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.models.model_utils import add_module_args
        from fengshen_tpu.trainer import add_trainer_args
        from fengshen_tpu.utils import UniversalCheckpoint
        parent_parser = add_module_args(parent_parser)
        parent_parser = add_trainer_args(parent_parser)
        parent_parser = UniversalDataModule.add_data_specific_args(
            parent_parser)
        parent_parser = UniversalCheckpoint.add_argparse_args(parent_parser)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, params=None, config=None,
                 num_labels: int = 2, **kwargs):
        self.args = args
        self.model_path = model
        model_type = "huggingface-auto"
        if config is None and model is not None:
            import json
            import os
            cfg_file = os.path.join(model, "config.json")
            if os.path.exists(cfg_file):
                with open(cfg_file) as f:
                    raw = json.load(f)
                model_type = raw.get("fengshen_model_type",
                                     raw.get("model_type",
                                             "huggingface-auto"))
                if model_type not in _model_dict:
                    model_type = "huggingface-auto"
                config = _model_dict[model_type][0].from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        if getattr(config, "num_labels", None) != num_labels and \
                num_labels is not None:
            config.num_labels = num_labels
        self.config = config
        self.model = _model_dict[model_type][1](config)

        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        self.params = params
        self._predict_fn = None

    # -- training --------------------------------------------------------
    def train(self, datasets: Any) -> None:
        """Reference: pipelines/text_classification.py:194-218."""
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.trainer import Trainer
        from fengshen_tpu.utils import UniversalCheckpoint

        collator = _Collator(
            self.tokenizer,
            max_length=getattr(self.args, "max_length", 512),
            texta_name=getattr(self.args, "texta_name", "sentence"),
            textb_name=getattr(self.args, "textb_name", "sentence2"),
            label_name=getattr(self.args, "label_name", "label"))
        if isinstance(datasets, str):
            from fengshen_tpu.data.fs_datasets import load_dataset
            datasets = load_dataset(datasets)
        datamodule = UniversalDataModule(tokenizer=self.tokenizer,
                                         collate_fn=collator,
                                         args=self.args, datasets=datasets)
        module = _TaskModule(self.args, self.model, self.config)
        if self.params is not None:
            module.init_params = lambda rng: self.params
        trainer = Trainer(self.args)
        trainer.callbacks.append(UniversalCheckpoint(self.args))
        state = trainer.fit(module, datamodule)
        self.params = state.params

    # -- inference -------------------------------------------------------
    def __call__(self, text, text_pair=None):
        if self.params is None:
            rng = jax.random.PRNGKey(0)
            self.params = self.model.init(
                rng, jnp.zeros((1, 8), jnp.int32))["params"]
        single = isinstance(text, str)
        texts = [text] if single else list(text)
        pairs = None
        if text_pair is not None:
            pairs = [text_pair] if single else list(text_pair)
        enc = self.tokenizer(texts, pairs, padding=True, truncation=True,
                             max_length=getattr(self.args, "max_length",
                                                512),
                             return_tensors="np")
        kwargs = {"attention_mask":
                  jnp.asarray(enc["attention_mask"], jnp.int32)}
        if "token_type_ids" in enc:
            kwargs["token_type_ids"] = jnp.asarray(enc["token_type_ids"],
                                                   jnp.int32)
        logits = self.model.apply({"params": self.params},
                                  jnp.asarray(enc["input_ids"], jnp.int32),
                                  **kwargs)
        probs = np.asarray(jax.nn.softmax(logits, axis=-1))
        results = [{"label": int(p.argmax()), "score": float(p.max())}
                   for p in probs]
        return results[0] if single else results


Pipeline = TextClassificationPipeline
