"""Sequence-tagging (NER) pipeline.

Port of reference: fengshen/pipelines/sequence_tagging.py:42-313 — same
train/__call__ contract with BIO decoding of predictions into entities.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.metrics.utils_ner import get_entities
from fengshen_tpu.models.megatron_bert import MegatronBertConfig
from fengshen_tpu.models.tagging import BertLinear, BertCrf
from fengshen_tpu.trainer.module import TrainModule

_model_dict = {
    "bert-linear": BertLinear,
    "bert-crf": BertCrf,
}


@dataclass
class _TaggingCollator:
    tokenizer: Any
    label2id: dict
    max_length: int = 256
    text_name: str = "text"
    label_name: str = "labels"

    def __call__(self, samples: list[dict]) -> dict:
        out = {"input_ids": [], "attention_mask": [], "labels": []}
        for s in samples:
            chars = list(s[self.text_name])[: self.max_length - 2]
            ids = self.tokenizer.convert_tokens_to_ids(chars)
            ids = [self.tokenizer.cls_token_id] + ids + \
                [self.tokenizer.sep_token_id]
            labels = [str(x) for x in s.get(self.label_name, [])]
            lab = [self.label2id.get(l, 0)
                   for l in labels][: self.max_length - 2]
            lab = [-100] + lab + [-100]
            pad = self.max_length - len(ids)
            out["input_ids"].append(ids + [self.tokenizer.pad_token_id or 0]
                                    * pad)
            out["attention_mask"].append([1] * len(ids) + [0] * pad)
            out["labels"].append(lab + [-100] * pad)
        return {k: np.asarray(v) for k, v in out.items()}


class _TaggingModule(TrainModule):
    def __init__(self, args, model, config):
        super().__init__(args)
        self.model = model
        self.config = config

    def init_params(self, rng):
        return self.model.init(rng, jnp.zeros((1, 16), jnp.int32))["params"]

    def training_loss(self, params, batch, rng):
        loss, logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            labels=batch["labels"], deterministic=False,
            rngs={"dropout": rng})
        valid = batch["labels"] != -100
        acc = ((logits.argmax(-1) == batch["labels"]) * valid).sum() / \
            jnp.maximum(valid.sum(), 1)
        return loss, {"acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


class SequenceTaggingPipeline:
    @staticmethod
    def add_pipeline_specific_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("sequence tagging")
        parser.add_argument("--max_length", default=256, type=int)
        parser.add_argument("--decode_type", default="linear", type=str,
                            choices=["linear", "crf"])
        parser.add_argument("--markup", default="bios", type=str)
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.models.model_utils import add_module_args
        from fengshen_tpu.trainer import add_trainer_args
        from fengshen_tpu.utils import UniversalCheckpoint
        parent_parser = add_module_args(parent_parser)
        parent_parser = add_trainer_args(parent_parser)
        parent_parser = UniversalDataModule.add_data_specific_args(
            parent_parser)
        parent_parser = UniversalCheckpoint.add_argparse_args(parent_parser)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, labels: Optional[list[str]] = None,
                 config=None, params=None,
                 backbone_type: str = "megatron_bert", **kwargs):
        self.args = args
        self.labels = labels or ["O"]
        self.label2id = {l: i for i, l in enumerate(self.labels)}
        self.id2label = {i: l for i, l in enumerate(self.labels)}
        decode_type = getattr(args, "decode_type", "linear") if args \
            else "linear"
        if config is None and model is not None:
            config = MegatronBertConfig.from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        self.config = config
        model_cls = _model_dict[
            "bert-crf" if decode_type == "crf" else "bert-linear"]
        self.model = model_cls(config, num_labels=len(self.labels),
                               backbone_type=backbone_type)
        self.decode_type = decode_type
        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        self.params = params

    def train(self, datasets: Any) -> None:
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.trainer import Trainer
        from fengshen_tpu.utils import UniversalCheckpoint
        collator = _TaggingCollator(
            self.tokenizer, self.label2id,
            max_length=getattr(self.args, "max_length", 256))
        if isinstance(datasets, str):
            from fengshen_tpu.data.fs_datasets import load_dataset
            datasets = load_dataset(datasets)
        datamodule = UniversalDataModule(tokenizer=self.tokenizer,
                                         collate_fn=collator,
                                         args=self.args, datasets=datasets)
        module = _TaggingModule(self.args, self.model, self.config)
        if self.params is not None:
            module.init_params = lambda rng: self.params
        trainer = Trainer(self.args)
        trainer.callbacks.append(UniversalCheckpoint(self.args))
        state = trainer.fit(module, datamodule)
        self.params = state.params

    def __call__(self, text: str):
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        chars = list(text)
        ids = [self.tokenizer.cls_token_id] + \
            self.tokenizer.convert_tokens_to_ids(chars) + \
            [self.tokenizer.sep_token_id]
        arr = jnp.asarray([ids], jnp.int32)
        mask = jnp.ones_like(arr)
        if self.decode_type == "crf":
            tags = self.model.apply({"params": self.params}, arr,
                                    attention_mask=mask, decode=True)
            pred = np.asarray(tags)[0][1:-1]
        else:
            logits = self.model.apply({"params": self.params}, arr,
                                      attention_mask=mask)
            pred = np.asarray(logits.argmax(-1))[0][1:-1]
        markup = getattr(self.args, "markup", "bios") if self.args \
            else "bios"
        entities = get_entities([self.id2label[int(p)] for p in pred],
                                markup=markup)
        return [{"entity": "".join(chars[s:e + 1]), "type": t,
                 "start": s, "end": e} for t, s, e in entities]


Pipeline = SequenceTaggingPipeline
