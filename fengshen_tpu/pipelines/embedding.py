"""Text-embedding pipeline: the Taiyi-CLIP text tower as a serving
surface, and the hook the embedding engine plugs into.

Follows the repo's pipeline contract (`__init__(args, model=...)`,
`__call__(text)`): encode the prompt with the Chinese-BERT text tower,
project into the CLIP joint space, L2-normalize
(models/clip/modeling_taiyi_clip.py `get_text_features`). `__call__`
is the one-request path; the `EmbeddingEngine`
(fengshen_tpu/serving/multimodal.py) instead drives `run_batch` so
co-arriving requests ride ONE jitted text-tower forward.

`small_test=True` builds a compact random-init tower with a built-in
byte tokenizer — serving tests and `make serve-bench-multimodal` run
on it without checkpoints. Real weights: convert the Taiyi-CLIP
checkpoint with `models.clip.convert` and inject `module=`/`params=`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.pipelines.image_generation import byte_encode


class Pipeline:
    """Taiyi-CLIP text-embedding pipeline.

    Inject `module` (a `TaiyiCLIPModel`)/`params` (+ optionally a
    tokenizer exposing `encode(text) -> list[int]`), or set
    `small_test=True` for the compact random-init tower.
    """

    task = "embedding"

    def __init__(self, args: Any = None, model: Optional[str] = None,
                 module: Any = None, params: Any = None,
                 tokenizer: Any = None, max_text_len: int = 16,
                 seed: int = 0, small_test: bool = False):
        if args is not None:
            max_text_len = getattr(args, "max_text_len", max_text_len)
        if module is None and small_test:
            module, params = self._build_small_test(seed)
        if module is None:
            if model is None:
                raise ValueError(
                    "embedding needs an injected module/params or "
                    "small_test=True")
            raise ValueError(
                "model= checkpoint loading is not wired for embedding; "
                "convert the Taiyi-CLIP checkpoint with "
                "models.clip.convert and inject module=/params= (or "
                "use small_test=True)")
        if params is None:
            raise ValueError("params are required alongside module")
        self.module = module
        self.params = params
        self.tokenizer = tokenizer
        self.max_text_len = int(max_text_len)
        self._embed_jit = jax.jit(self._embed)

    @staticmethod
    def _build_small_test(seed: int):
        from fengshen_tpu.models.bert import BertConfig
        from fengshen_tpu.models.clip.modeling_taiyi_clip import (
            CLIPVisionConfig, TaiyiCLIPModel)
        text_cfg = BertConfig(vocab_size=128, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=2,
                              intermediate_size=64,
                              max_position_embeddings=64,
                              dtype="float32")
        module = TaiyiCLIPModel(text_cfg,
                                CLIPVisionConfig.small_test_config())
        ids = jnp.zeros((1, 8), jnp.int32)
        pixels = jnp.zeros((1, 32, 32, 3), jnp.float32)
        params = jax.jit(lambda r: module.init(
            r, ids, pixels)["params"])(jax.random.PRNGKey(seed))
        return module, params

    # ---- engine integration -----------------------------------------

    def encode(self, text: str) -> np.ndarray:
        if self.tokenizer is not None:
            ids = list(self.tokenizer.encode(text))[:self.max_text_len]
            ids += [0] * (self.max_text_len - len(ids))
            return np.asarray(ids, np.int32)
        vocab = self.module.text_config.vocab_size
        return byte_encode(text, vocab, self.max_text_len)

    def warmup_input(self) -> str:
        return "warmup"

    def _embed(self, params, input_ids):
        # through __call__ (the module's compact entry point) with
        # pixel_values=None: only the text tower runs
        text_emb, _, _ = self.module.apply({"params": params},
                                           input_ids)
        return text_emb

    def run_batch(self, texts: list) -> list:
        """The EmbeddingEngine hook: one jitted text-tower forward for
        the whole micro-batch."""
        from fengshen_tpu.observability import get_registry, span
        ids = jnp.asarray(np.stack([self.encode(t) for t in texts]))
        with span("pipeline/embed_batch"):
            emb = np.asarray(jax.block_until_ready(
                self._embed_jit(self.params, ids)))
        get_registry().counter(
            "fstpu_pipeline_embeddings_total",
            "embeddings computed by the embedding pipeline"
        ).inc(len(texts))
        return [{"embedding": row.astype(float).tolist(),
                 "dim": int(emb.shape[-1])} for row in emb]

    # ---- legacy one-request path ------------------------------------

    def __call__(self, input_text: str) -> dict:
        return self.run_batch([input_text])[0]

    @staticmethod
    def add_pipeline_specific_args(parser):
        parser.add_argument("--max_text_len", default=16, type=int)
        return parser
