"""Task pipelines (reference: fengshen/pipelines/).

Each submodule exposes a ``Pipeline`` class with the reference's contract:
``__init__(args, model=...)``, ``train(datasets)``, ``__call__(text)`` and
``add_pipeline_specific_args(parser)``
(reference: fengshen/pipelines/text_classification.py:134-234).
"""

#: registered task names — kept in sync with the submodules
TASKS: list[str] = ["text_classification", "sequence_tagging",
                    "multiplechoice", "information_extraction", "tcbert",
                    "text_generation"]
