"""Text-to-image pipeline: the Taiyi Stable Diffusion inference surface
and the hook the batch-image serving engine plugs into.

Follows the repo's pipeline contract (`__init__(args, model=...)`,
`__call__(text)`) for the latent-diffusion pipeline
(models/stable_diffusion/modeling_taiyi_sd.py): encode the prompt with
the Chinese text tower, walk a subsampled DDPM schedule over latent
noise, decode with the VAE. `__call__` is the one-request path; the
`BatchImageEngine` (fengshen_tpu/serving/multimodal.py) instead drives
`run_batch` so co-arriving prompts ride ONE jitted denoise loop.

Released Taiyi-SD weights are three towers (text encoder + diffusers
unet/vae) — convert them with `models.stable_diffusion.convert` and
inject `module=`/`params=`. `small_test=True` builds the compact
random-init towers with a built-in byte tokenizer — the serving tests
and `make serve-bench-multimodal` run on it without any checkpoint or
tokenizer dependency.
"""

from __future__ import annotations

import base64
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def byte_encode(text: str, vocab_size: int, max_len: int) -> np.ndarray:
    """Dependency-free tokenizer for the small-test towers: bytes
    folded into [3, vocab), padded with 0 to `max_len`. Deterministic,
    so request→image is reproducible across processes."""
    ids = [3 + (b % (vocab_size - 3))
           for b in text.encode("utf-8")[:max_len]]
    return np.asarray(ids + [0] * (max_len - len(ids)), np.int32)


class Pipeline:
    """Taiyi Stable Diffusion text-to-image pipeline.

    Either pass `model` (an HF diffusers checkpoint directory) or
    inject `module`/`params` (+ optionally `tokenizer`) directly, or
    set `small_test=True` for the compact random-init towers. The
    tokenizer needs `encode(text) -> list[int]`; None falls back to
    the byte tokenizer above.
    """

    task = "image_generation"

    def __init__(self, args: Any = None, model: Optional[str] = None,
                 module: Any = None, params: Any = None,
                 tokenizer: Any = None, image_size: int = 32,
                 num_inference_steps: int = 4, max_text_len: int = 16,
                 seed: int = 0, small_test: bool = False):
        if args is not None:
            image_size = getattr(args, "image_size", image_size)
            num_inference_steps = getattr(args, "num_inference_steps",
                                          num_inference_steps)
        if module is None and small_test:
            module, params = self._build_small_test(seed)
        if module is None:
            if model is None:
                raise ValueError(
                    "image_generation needs an injected module/params "
                    "or small_test=True")
            # a released Taiyi-SD checkpoint is THREE towers (text
            # encoder + diffusers unet/vae); assemble the
            # TaiyiStableDiffusion params via
            # models.stable_diffusion.convert (load_diffusers_pipeline
            # + the bert converter) and inject module=/params=
            raise ValueError(
                "model= checkpoint assembly is not wired for "
                "image_generation; convert the towers with "
                "models.stable_diffusion.convert and inject "
                "module=/params= (or use small_test=True)")
        if params is None:
            raise ValueError("params are required alongside module")
        self.module = module
        self.params = params
        self.tokenizer = tokenizer
        self.image_size = int(image_size)
        self.num_inference_steps = int(num_inference_steps)
        self.max_text_len = int(max_text_len)
        self.seed = seed
        self._n_calls = 0
        self._generate_jit = jax.jit(self._generate)

    @staticmethod
    def _build_small_test(seed: int):
        from fengshen_tpu.models.bert import BertConfig
        from fengshen_tpu.models.stable_diffusion.autoencoder_kl import \
            VAEConfig
        from fengshen_tpu.models.stable_diffusion.modeling_taiyi_sd import \
            TaiyiStableDiffusion
        from fengshen_tpu.models.stable_diffusion.unet import UNetConfig
        text_cfg = BertConfig(vocab_size=128, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=2,
                              intermediate_size=64,
                              max_position_embeddings=64,
                              dtype="float32")
        module = TaiyiStableDiffusion(
            text_cfg, VAEConfig.small_test_config(),
            UNetConfig.small_test_config(cross_attention_dim=32))
        ids = jnp.zeros((1, 8), jnp.int32)
        pixels = jnp.zeros((1, 32, 32, 3), jnp.float32)
        t = jnp.zeros((1,), jnp.int32)
        noise = jnp.zeros((1, 16, 16, 4), jnp.float32)

        def init_all(m, ids, pixels, t, noise):
            # the decoder convs are inline-compact, so the init trace
            # must walk decode_image too or its params never exist
            pred, latents = m(ids, pixels, t, noise)
            m.decode_image(latents)
            return pred

        params = jax.jit(lambda r: module.init(
            r, ids, pixels, t, noise,
            method=init_all)["params"])(jax.random.PRNGKey(seed))
        return module, params

    # ---- engine integration -----------------------------------------

    def encode(self, text: str) -> np.ndarray:
        if self.tokenizer is not None:
            ids = list(self.tokenizer.encode(text))[:self.max_text_len]
            ids += [0] * (self.max_text_len - len(ids))
            return np.asarray(ids, np.int32)
        vocab = self.module.text_config.vocab_size
        return byte_encode(text, vocab, self.max_text_len)

    def warmup_input(self) -> str:
        return "warmup"

    def _generate(self, params, input_ids, rng):
        """One jitted batch: text encode → subsampled DDPM walk →
        VAE decode → [0,1] pixels. Python loop over the (static)
        inference schedule unrolls into one program."""
        from fengshen_tpu.models.stable_diffusion.scheduler import \
            DDPMScheduler
        module = self.module
        scheduler = DDPMScheduler()
        batch = input_ids.shape[0]
        text = module.apply({"params": params}, input_ids,
                            method=module.encode_text)
        factor = 2 ** (len(module.vae_config.channel_mults) - 1)
        latents = jax.random.normal(
            rng, (batch, self.image_size // factor,
                  self.image_size // factor,
                  module.vae_config.latent_channels))
        T = scheduler.num_train_timesteps
        steps = np.linspace(T - 1, 0, self.num_inference_steps,
                            dtype=np.int64)
        for i, t in enumerate(steps):
            t_b = jnp.full((batch,), int(t), jnp.int32)
            pred = module.apply({"params": params}, latents, t_b, text,
                                method=module.denoise)
            prev_t = int(steps[i + 1]) if i + 1 < len(steps) else -1
            latents = scheduler.step(pred, int(t), latents,
                                     prev_timestep=prev_t)
        pixels = module.apply({"params": params}, latents,
                              method=module.decode_image)
        return jnp.clip((pixels + 1.0) / 2.0, 0.0, 1.0)

    def run_batch(self, texts: list) -> list:
        """The BatchImageEngine hook: one jitted denoise loop for the
        whole micro-batch; per-request RNG folds in the call counter so
        repeated identical prompts differ (and the batch as a whole is
        reproducible from `seed`)."""
        from fengshen_tpu.observability import get_registry, span
        self._n_calls += 1
        ids = jnp.asarray(np.stack([self.encode(t) for t in texts]))
        rng = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                 self._n_calls)
        with span("pipeline/image_batch"):
            images = np.asarray(
                jax.block_until_ready(
                    self._generate_jit(self.params, ids, rng)))
        get_registry().counter(
            "fstpu_pipeline_images_total",
            "images generated by the batch-image pipeline"
        ).inc(len(texts))
        return [self._pack(img) for img in images]

    @staticmethod
    def _pack(img: np.ndarray) -> dict:
        """JSON-safe result: raw uint8 RGB bytes, base64. No PIL/png
        dependency — clients reshape from `shape`."""
        u8 = (img * 255.0 + 0.5).astype(np.uint8)
        return {"image_b64": base64.b64encode(u8.tobytes()).decode(),
                "shape": list(u8.shape), "dtype": "uint8"}

    # ---- legacy one-request path ------------------------------------

    def __call__(self, input_text: str) -> dict:
        return self.run_batch([input_text])[0]

    @staticmethod
    def add_pipeline_specific_args(parser):
        parser.add_argument("--image_size", default=32, type=int)
        parser.add_argument("--num_inference_steps", default=4, type=int)
        return parser
