"""Text-generation pipeline: the generation-side `Pipeline` surface and
the hook the continuous-batching serving engine plugs into.

Follows the repo's pipeline contract (`__init__(args, model=...)`,
`__call__(text)` — reference: fengshen/pipelines/text_classification.py
:134-234) for a decoder-only causal LM. `__call__` is the LEGACY
serving path: one batch-1 `utils.generate.generate` per call. The
continuous engine (`fengshen_tpu/serving/`) instead drives the same
model/params through its slot pool; this pipeline supplies what the
engine needs — `module`, `params`, `encode`/`decode`, and the
generation defaults (`engine_config_kwargs`).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Pipeline:
    """Causal-LM generation pipeline (LLaMA family).

    Either pass `model` (an HF llama checkpoint directory — loaded via
    `models.llama.convert.load_hf_pretrained` + AutoTokenizer, the
    ziya_inference idiom) or inject `module`/`params`/`tokenizer`
    directly (tests, custom checkpoints). The tokenizer needs
    `encode(text) -> list[int]` / `decode(ids) -> str` plus
    `eos_token_id`/`pad_token_id` attributes.
    """

    task = "text_generation"

    def __init__(self, args: Any = None, model: Optional[str] = None,
                 module: Any = None, params: Any = None,
                 tokenizer: Any = None,
                 max_new_tokens: int = 64,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 0.0,
                 repetition_penalty: float = 1.0,
                 min_length: int = 0, seed: int = 0):
        if args is not None:
            # the fengshen-pipeline CLI parses our
            # add_pipeline_specific_args flags into `args`
            max_new_tokens = getattr(args, "max_new_tokens",
                                     max_new_tokens)
            do_sample = getattr(args, "do_sample", do_sample)
            temperature = getattr(args, "temperature", temperature)
            top_k = getattr(args, "top_k", top_k)
            top_p = getattr(args, "top_p", top_p)
        if module is None:
            if model is None:
                raise ValueError(
                    "text_generation needs either model=<hf checkpoint "
                    "dir> or an injected module/params/tokenizer")
            from transformers import AutoTokenizer

            from fengshen_tpu.models.llama import LlamaForCausalLM
            from fengshen_tpu.models.llama.convert import \
                load_hf_pretrained
            config, params = load_hf_pretrained(model)
            module = LlamaForCausalLM(config)
            if tokenizer is None:
                tokenizer = AutoTokenizer.from_pretrained(model)
        if params is None:
            raise ValueError("params are required alongside module")
        self.module = module
        self.params = params
        self.tokenizer = tokenizer
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id if eos_token_id is not None \
            else getattr(tokenizer, "eos_token_id", None)
        pad = pad_token_id if pad_token_id is not None \
            else getattr(tokenizer, "pad_token_id", None)
        self.pad_token_id = 0 if pad is None else int(pad)
        self.sample_kw = dict(do_sample=do_sample,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p,
                              repetition_penalty=repetition_penalty,
                              min_length=min_length)
        self.seed = seed
        self._n_calls = 0

    # ---- engine integration -----------------------------------------

    def encode(self, text: str) -> np.ndarray:
        return np.asarray(self.tokenizer.encode(text), np.int32)

    def decode(self, token_ids) -> str:
        ids = [int(t) for t in token_ids]
        if self.eos_token_id is not None and self.eos_token_id in ids:
            ids = ids[:ids.index(self.eos_token_id)]
        return self.tokenizer.decode(ids)

    def engine_config_kwargs(self) -> dict:
        """Generation defaults for `serving.EngineConfig(**...)`."""
        return dict(max_new_tokens=self.max_new_tokens,
                    eos_token_id=self.eos_token_id,
                    pad_token_id=self.pad_token_id, seed=self.seed,
                    **self.sample_kw)

    # ---- legacy one-request path ------------------------------------

    def __call__(self, input_text: str,
                 max_new_tokens: Optional[int] = None) -> str:
        ids = self.encode(input_text)
        out = self.generate_ids(
            ids, max_new_tokens or self.max_new_tokens)
        return self.decode(out)

    def generate_ids(self, ids: np.ndarray,
                     max_new_tokens: int) -> list:
        """Batch-1 sequential decode (the legacy engine). Counted and
        span-timed on the global registry (docs/observability.md) so
        the simple-engine path shows up on /metrics like the
        continuous engine does."""
        from fengshen_tpu.observability import get_registry, span
        from fengshen_tpu.utils.generate import generate
        self._n_calls += 1
        with span("pipeline/generate"):
            out = generate(
                self.module, self.params, jnp.asarray(ids)[None],
                max_new_tokens=max_new_tokens,
                eos_token_id=self.eos_token_id,
                pad_token_id=self.pad_token_id,
                rng=jax.random.PRNGKey(self.seed + self._n_calls),
                **self.sample_kw)
        out = np.asarray(out)[0, len(ids):].tolist()
        # generate() is fixed-shape: the row is always max_new_tokens
        # long with pad after eos — count only the real tokens (up to
        # and including eos), or the throughput metric inflates by the
        # pad tail on every early stop
        n_real = (out.index(self.eos_token_id) + 1
                  if self.eos_token_id is not None
                  and self.eos_token_id in out else len(out))
        get_registry().counter(
            "fstpu_pipeline_generated_tokens_total",
            "tokens generated by the legacy batch-1 pipeline path"
        ).inc(n_real)
        return out

    @staticmethod
    def add_pipeline_specific_args(parser):
        parser.add_argument("--max_new_tokens", default=64, type=int)
        parser.add_argument("--do_sample", action="store_true")
        parser.add_argument("--temperature", default=1.0, type=float)
        parser.add_argument("--top_k", default=0, type=int)
        parser.add_argument("--top_p", default=0.0, type=float)
        return parser
