"""Declarative logical-axis sharding (docs/sharding.md).

Named logical axes (`axes.LOGICAL_AXES`) + ONE rules table
(`rules.DEFAULT_LOGICAL_AXIS_RULES`: logical axis → mesh axis or None)
replace per-model hand-written PartitionSpec regex tables. Models
declare ``PARAM_LOGICAL_AXES`` (regex → logical tuple);
:func:`to_partition_rules` resolves them against the active table into
the regex → PartitionSpec lists the existing partition/trainer/offload
machinery consumes unchanged; :func:`with_logical_constraint`
annotates activations; :func:`rules_fingerprint` puts the table into
the AOT cache key.
"""

from fengshen_tpu.sharding.axes import LOGICAL_AXES, LOGICAL_AXIS_SET
from fengshen_tpu.sharding.rules import (DEFAULT_LOGICAL_AXIS_RULES,
                                         get_rules, resolve_spec,
                                         rules_fingerprint, set_rules,
                                         to_partition_rules, use_rules,
                                         validate_rules,
                                         with_logical_constraint)

__all__ = [
    "LOGICAL_AXES",
    "LOGICAL_AXIS_SET",
    "DEFAULT_LOGICAL_AXIS_RULES",
    "get_rules",
    "set_rules",
    "use_rules",
    "validate_rules",
    "resolve_spec",
    "to_partition_rules",
    "with_logical_constraint",
    "rules_fingerprint",
]
