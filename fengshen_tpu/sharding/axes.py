"""Logical-axis vocabulary: the NAMES parameter/activation dimensions
carry, independent of how (or whether) the mesh shards them.

This is the declarative half of the sharding subsystem
(docs/sharding.md). A model annotates each parameter dimension with a
logical role from this vocabulary — ``vocab``, ``embed``, ``heads``,
``mlp``, ``conv_out``, … — and ONE rules table
(:data:`fengshen_tpu.sharding.rules.DEFAULT_LOGICAL_AXIS_RULES`) maps
each role onto a mesh axis from ``fengshen_tpu/parallel/mesh.py`` (or
None = replicated). Changing how the whole fleet shards MLPs is then
one table edit, not a hunt through per-model regex tables — the
TorchTitan/Megatron argument (PAPERS.md: arxiv 2410.06511, 2104.04473)
for declarative, composable parallelism.

fslint's ``partition-spec-axes`` rule parses THIS file statically (the
``LOGICAL_AXES`` tuple below) to validate every rules table and every
``*PARAM_LOGICAL_AXES`` annotation in the package — an axis name not
declared here fails the fast lane, it does not silently replicate.
"""

from __future__ import annotations

# Every logical dimension name the package may use. Keep the tuple
# flat, literal, and sorted by theme — fslint reads it with `ast`, so
# no computed entries.
LOGICAL_AXES: tuple = (
    # activations
    "batch",        # examples dim of activations / optimizer-free data
    "seq",          # sequence/time dim of activations
    # embeddings / projections
    "vocab",        # vocabulary rows of embedding + lm_head matrices
    "embed",        # hidden/model dim (d_model) of weights
    "heads",        # attention-head product dim (n_head * head_dim):
                    # Megatron column-parallel attention output
    "kv",           # key/value head product dim (GQA towers)
    "mlp",          # feed-forward inner dim (column-parallel in,
                    # row-parallel out)
    "expert",       # MoE expert dim of stacked expert weights
    "layers",       # stacked-layer dim of scan_layers parameter trees
    # convolutional towers (NHWC kernels are [kh, kw, cin, cout])
    "conv_kernel",  # spatial kh/kw dims of conv kernels
    "conv_in",      # input-channel (contraction) dim of conv kernels
    "conv_out",     # output-channel dim of conv kernels
    # deliberately-unsharded roles (mapped to None in the default
    # table; the NAME records why, see docs/sharding.md)
    "relpos",       # relative/absolute position-embedding feature dim:
                    # products of iota-derived sin|cos concats must not
                    # become a sharded matmul contraction (the
                    # concat-contraction miscompile, docs/sharding.md
                    # "Root cause")
    "norm",         # norm scale/bias vectors — stats reduce over the
                    # full feature dim, never a shard
)

#: Fast membership checks for the runtime validators.
LOGICAL_AXIS_SET = frozenset(LOGICAL_AXES)
