"""The declarative rules table: logical axis → mesh axis (or None).

One table answers "how does this deployment shard?" for every model in
the package (docs/sharding.md). Models annotate parameter dimensions
with logical-axis names (``*PARAM_LOGICAL_AXES`` tables: regex on the
param path → tuple of logical names, the same path-matching contract
``parallel.partition.match_partition_rules`` already speaks) and
:func:`to_partition_rules` resolves them into the regex →
``PartitionSpec`` lists every existing consumer
(``make_shardings`` / ``create_sharded_state`` / the offload policy)
takes unchanged. Activations go through
:func:`with_logical_constraint`, optimizer state inherits the param
specs as before.

The resolved sharding is part of a compiled program's identity:
:func:`rules_fingerprint` serializes the active table into the AOT
cache key (docs/aot_cache.md) so two deployments with different tables
can never cross-hit one executable cache.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from fengshen_tpu.parallel.mesh import (BATCH_AXES, DATA_AXIS, EXPERT_AXIS,
                                        FSDP_AXIS, SEQUENCE_AXIS,
                                        TENSOR_AXIS)
from fengshen_tpu.sharding.axes import LOGICAL_AXIS_SET

#: The default deployment table — the sharding story of the whole
#: package in one place. Megatron conventions (PAPERS.md arxiv
#: 2104.04473): column-parallel projections put their OUTPUT dim
#: (heads/kv/mlp) on the tensor axis, row-parallel projections their
#: INPUT dim; the other weight dim takes fsdp (ZeRO-3-style param
#: sharding); vocab is tensor-parallel for the vocab-parallel
#: embedding + CE. ``relpos`` and ``norm`` are deliberately None —
#: see docs/sharding.md "Root cause" for why relpos must never shard.
DEFAULT_LOGICAL_AXIS_RULES: tuple = (
    ("batch", BATCH_AXES),
    ("seq", SEQUENCE_AXIS),
    ("vocab", TENSOR_AXIS),
    ("embed", FSDP_AXIS),
    ("heads", TENSOR_AXIS),
    ("kv", TENSOR_AXIS),
    ("mlp", TENSOR_AXIS),
    ("expert", EXPERT_AXIS),
    ("layers", None),
    ("conv_kernel", None),
    ("conv_in", None),
    ("conv_out", FSDP_AXIS),
    ("relpos", None),
    ("norm", None),
)

#: Mesh-axis names the table may map onto (mirrors
#: ``parallel.mesh.MESH_AXES``; kept literal so the table validates
#: without building a mesh).
_MESH_AXIS_SET = frozenset({DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS,
                            TENSOR_AXIS, EXPERT_AXIS, "pipe"})

_active = threading.local()


def validate_rules(rules: Sequence[Tuple[str, Any]]) -> None:
    """Reject a malformed table loudly at definition time — an unknown
    logical axis would otherwise KeyError deep inside resolution, and
    an unknown mesh axis would silently replicate (the exact failure
    fslint's partition-spec-axes rule exists to catch statically)."""
    seen = set()
    for entry in rules:
        if not isinstance(entry, (tuple, list)) or len(entry) != 2:
            raise ValueError(f"rules entry {entry!r} is not a "
                             "(logical_axis, mesh_axis) pair")
        logical, mesh_axis = entry
        if logical not in LOGICAL_AXIS_SET:
            raise ValueError(
                f"unknown logical axis {logical!r} — declare it in "
                "fengshen_tpu/sharding/axes.py (LOGICAL_AXES)")
        if logical in seen:
            raise ValueError(f"logical axis {logical!r} mapped twice")
        seen.add(logical)
        axes = mesh_axis if isinstance(mesh_axis, (tuple, list)) \
            else (mesh_axis,)
        for a in axes:
            if a is not None and a not in _MESH_AXIS_SET:
                raise ValueError(
                    f"rules map {logical!r} to unknown mesh axis "
                    f"{a!r} (mesh axes: "
                    f"{', '.join(sorted(_MESH_AXIS_SET))})")


def get_rules() -> tuple:
    """The active table: the default unless a `use_rules` scope or
    `set_rules` override is in effect."""
    return getattr(_active, "rules", None) or DEFAULT_LOGICAL_AXIS_RULES


def set_rules(rules: Optional[Sequence[Tuple[str, Any]]]) -> None:
    """Install `rules` as the active table (None restores the
    default). Validates eagerly."""
    if rules is not None:
        validate_rules(rules)
        rules = tuple((k, tuple(v) if isinstance(v, list) else v)
                      for k, v in rules)
    _active.rules = rules


class use_rules:
    """Scoped table override::

        with use_rules(my_table):
            shardings = make_shardings(model.partition_rules(), ...)
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, Any]]]):
        self._rules = rules

    def __enter__(self):
        self._prev = getattr(_active, "rules", None)
        set_rules(self._rules)
        return get_rules()

    def __exit__(self, *exc):
        _active.rules = self._prev
        return False


def resolve_spec(logical_axes: Sequence[Optional[str]],
                 rules: Optional[Sequence[Tuple[str, Any]]] = None) -> P:
    """One logical-axes tuple → a PartitionSpec under `rules` (default:
    the active table). None entries stay None (explicitly replicated
    dims); a logical name absent from the table resolves to None too —
    an UNKNOWN name (not in the vocabulary) raises."""
    table = dict(rules if rules is not None else get_rules())
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        if name not in LOGICAL_AXIS_SET:
            raise ValueError(
                f"unknown logical axis {name!r} — declare it in "
                "fengshen_tpu/sharding/axes.py (LOGICAL_AXES)")
        mesh_axis = table.get(name)
        out.append(tuple(mesh_axis) if isinstance(mesh_axis, list)
                   else mesh_axis)
    return P(*out) if out else P(None)


def to_partition_rules(
        param_axes: Sequence[Tuple[str, Sequence[Optional[str]]]],
        rules: Optional[Sequence[Tuple[str, Any]]] = None) -> list:
    """Resolve a model's ``PARAM_LOGICAL_AXES`` table (regex → logical
    tuple) into the regex → PartitionSpec list the whole existing
    machinery consumes (`match_partition_rules`, `make_shardings`,
    `create_sharded_state`, offload policy) — the migration seam that
    keeps every downstream consumer unchanged."""
    return [(pattern, resolve_spec(axes, rules))
            for pattern, axes in param_axes]


def with_logical_constraint(x, logical_axes: Sequence[Optional[str]],
                            rules: Optional[Sequence[Tuple[str, Any]]]
                            = None, mesh=None):
    """Constrain an ACTIVATION by logical-axis names — the declarative
    form of `parallel.with_sharding_constraint`. Outside a mesh scope
    it degrades to identity like the underlying helper, so model code
    can annotate unconditionally."""
    from fengshen_tpu.parallel.partition import with_sharding_constraint
    return with_sharding_constraint(x, resolve_spec(logical_axes, rules),
                                    mesh=mesh)


def _canonical(rules: Sequence[Tuple[str, Any]]) -> list:
    return sorted((k, list(v) if isinstance(v, (tuple, list)) else v)
                  for k, v in rules)


def rules_fingerprint(
        rules: Optional[Sequence[Tuple[str, Any]]] = None) -> str:
    """Deterministic digest of a table (default: the active one) for
    the AOT cache key: programs compiled under different tables bake
    different collectives into the executable, so the table is part of
    the program identity exactly like the kernel dispatch table
    (docs/aot_cache.md, docs/kernels.md). Order-insensitive — two
    spellings of the same mapping hit the same cache."""
    payload = json.dumps(
        _canonical(rules if rules is not None else get_rules()),
        separators=(",", ":"), sort_keys=True)
    return "lar1:" + hashlib.sha256(payload.encode()).hexdigest()[:16]
