"""Resilience subsystem: step guards, retrying loaders, fault injection.

Wired through the trainer (in-graph NaN/spike step guards +
rewind-on-divergence), the data layer (`ResilientLoader` retry/backoff
wrapper), and the checkpoint layer (corrupt-step fallback in
`UniversalCheckpoint.maybe_restore`). `FaultPlan` is the deterministic
fault-injection harness that drives all of it from fast CPU tests —
see docs/fault_tolerance.md.
"""

from fengshen_tpu.resilience.guards import guarded_apply, step_ok
from fengshen_tpu.resilience.loader import ResilientLoader
from fengshen_tpu.resilience.faults import (FaultPlan, FaultyLoader,
                                            InjectedLoaderFault,
                                            truncate_checkpoint_step)

__all__ = ["guarded_apply", "step_ok", "ResilientLoader", "FaultPlan",
           "FaultyLoader", "InjectedLoaderFault",
           "truncate_checkpoint_step"]
