"""ResilientLoader — bounded retry around any train/val dataloader.

TB-scale corpora live on network storage; a transient read error
mid-epoch should cost a backoff sleep, not the run. The wrapper
re-enters the wrapped loader (`iter(loader)`) after a failure. Loaders
driven by the stateful resumable samplers (the trainer's train path —
`PretrainingRandomSampler` advances `consumed_samples` as it yields,
and advertises it with `resumes_mid_epoch`) resume mid-epoch; for
every other (deterministic) loader the wrapper fast-forwards past the
batches it already delivered, so a retry never re-yields — and never
double-counts — earlier batches. `resumable` overrides the
auto-detection for custom loaders that keep their own cursor.

Semantics per failure:
- retry up to `max_retries` times with exponential backoff
  (`backoff_base * 2**attempt`) plus deterministic jitter;
- once retries are exhausted, consume one unit of the per-epoch
  `skip_batch_budget`: the loader advances past the poison batch via
  the cooperative `skip_next()` protocol (`DataLoader` implements it
  by pulling one batch of indices from its sampler without fetching).
  The budget applies only to resumable loaders — a restart-on-iter
  loader re-produces the poison batch on every re-entry, so no
  wrapper can skip it and pretending otherwise would burn the budget
  on one batch while logging skips that never happened;
- with the budget exhausted too, re-raise the last error — a loader
  that is down stays an error, never a silent zero-step epoch.

Counters (`retries_total`, `skipped_total`) and per-event structured
log entries (`loader_retry` / `loader_skip_batch`) make the noise
visible in metrics.jsonl — and, mirrored onto the observability
registry's `fstpu_loader_*` counters (docs/observability.md), on any
`/metrics` scrape: flaky storage shows up on the same dashboard as the
throughput it is eroding.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional

from fengshen_tpu.observability import get_registry


class ResilientLoader:
    def __init__(self, loader: Any, max_retries: int = 3,
                 backoff_base: float = 0.5, skip_batch_budget: int = 0,
                 log: Optional[Callable[[dict], None]] = None,
                 stage: str = "train",
                 sleep: Callable[[float], None] = time.sleep,
                 jitter_seed: int = 0,
                 resumable: Optional[bool] = None):
        self.loader = loader
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.skip_batch_budget = int(skip_batch_budget)
        self._log = log or (lambda entry: None)
        self.stage = stage
        self._sleep = sleep
        self._jitter = random.Random(jitter_seed)
        self.retries_total = 0
        #: cumulative skipped batches — the trainer snapshots this at
        #: fetch time (see _prefetch) to fold skipped stream positions
        #: into consumed_samples exactly at the training frontier
        self.skipped_total = 0
        reg = get_registry()
        self._c_retries = reg.counter(
            "fstpu_loader_retries_total",
            "loader read retries", labelnames=("stage",))
        self._c_skipped = reg.counter(
            "fstpu_loader_skipped_batches_total",
            "poison batches skipped after retries exhausted",
            labelnames=("stage",))
        if resumable is None:
            # stateful samplers advertise mid-epoch resume; anything
            # else is assumed deterministic-from-iter() and gets the
            # fast-forward treatment after a re-entry
            resumable = bool(getattr(getattr(loader, "sampler", None),
                                     "resumes_mid_epoch", False))
        self.resumable = bool(resumable)

    # -- passthrough surface (len / peek / num_samples / ...) ----------
    def __getattr__(self, name: str):
        return getattr(self.loader, name)

    def __len__(self) -> int:
        return len(self.loader)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    # -- iteration -----------------------------------------------------
    def __iter__(self):
        skipped_this_epoch = 0
        yielded = 0  # batches delivered downstream this epoch
        fast_forward = 0  # batches to discard after a re-entry
        it = iter(self.loader)
        while True:
            attempt = 0
            while True:
                try:
                    while fast_forward:
                        next(it)
                        fast_forward -= 1
                    batch = next(it)
                    break
                except StopIteration:
                    return
                except Exception as e:  # noqa: BLE001 — bounded retry;
                    # re-raised below once retries + skip budget exhaust
                    attempt += 1
                    self.retries_total += 1
                    self._c_retries.labels(self.stage).inc()
                    if attempt > self.max_retries:
                        if self.resumable and \
                                skipped_this_epoch < self.skip_batch_budget:
                            skipped_this_epoch += 1
                            self.skipped_total += 1
                            self._c_skipped.labels(self.stage).inc()
                            self._log({"event": "loader_skip_batch",
                                       "stage": self.stage,
                                       "skipped_this_epoch":
                                           skipped_this_epoch,
                                       "error": repr(e)[:200]})
                            it = self._reenter(yielded, skip=True)
                            attempt = 0
                            continue
                        raise
                    delay = self.backoff_base * (2 ** (attempt - 1))
                    delay *= 1.0 + 0.25 * self._jitter.random()
                    self._log({"event": "loader_retry",
                               "stage": self.stage, "attempt": attempt,
                               "delay_s": round(delay, 4),
                               "error": repr(e)[:200]})
                    self._sleep(delay)
                    it = self._reenter(yielded)
                    fast_forward = 0 if self.resumable else yielded
            yielded += 1
            yield batch

    def _reenter(self, yielded: int, skip: bool = False):
        """A generator that raised is dead: re-enter the loader.
        Resumable samplers continue mid-epoch on their own; for a skip,
        cooperative loaders advance past the poison batch via
        `skip_next()`."""
        if skip:
            skip_fn = getattr(self.loader, "skip_next", None)
            if callable(skip_fn):
                skip_fn()
        return iter(self.loader)
