"""Deterministic fault injection for trainer resilience tests.

Every resilience behavior (step guards, rewind, loader retry,
preemption autosave, corrupt-checkpoint fallback) must be exercisable
by fast CPU tests — chaos that only fires on a real pod is untestable
chaos. A `FaultPlan` describes WHEN faults fire in deterministic step /
batch coordinates and installs through the trainer's public hook
surface:

- `nan_loss_at_steps`: poison the in-graph loss with NaN when the
  (0-based) `TrainState.step` counter hits one of these values — the
  injection is compiled into the step program, so it exercises the
  guard exactly where a real numeric blowup would.
- `sigterm_at_step`: deliver a REAL `SIGTERM` to this process via
  `os.kill` when `trainer.global_step` crosses the value, driving the
  actual signal-handler → autosave → clean-exit path.
- `loader_raise_at`: {global_batch_index: times} — `wrap_datamodule`
  makes the train loader raise `InjectedLoaderFault` that many times
  BEFORE yielding the given batch (no sample is consumed by a failed
  attempt, so a retried run is batch-for-batch identical to a clean
  one).
- `truncate_checkpoint_step(path, step)`: module-level helper that
  destroys payload data inside an already-committed checkpoint step
  directory, simulating a half-written / bit-rotted checkpoint that
  `maybe_restore` must reject and fall back from.

After a rewind the trainer replays the same step numbers; with
`clear_nan_on_rewind` (default) the plan disarms its NaN injections on
rewind and the trainer rebuilds the step program, so the replayed
window runs clean — matching the real-world case where the rewound run
sees fresh data.
"""

from __future__ import annotations

import os
import signal
from typing import Any, Iterable, Optional


class InjectedLoaderFault(IOError):
    """Marker exception for injected loader failures."""


class FaultPlan:
    def __init__(self, nan_loss_at_steps: Iterable[int] = (),
                 sigterm_at_step: Optional[int] = None,
                 loader_raise_at: Optional[dict] = None,
                 clear_nan_on_rewind: bool = True):
        self.nan_loss_at_steps = frozenset(
            int(s) for s in nan_loss_at_steps)
        self.sigterm_at_step = sigterm_at_step
        self.loader_raise_at = dict(loader_raise_at or {})
        self.clear_nan_on_rewind = clear_nan_on_rewind
        self.fired: list = []

    # -- installation ---------------------------------------------------
    def install(self, trainer: Any) -> "FaultPlan":
        """Arm the plan on a Trainer: NaN injection is read by the step
        builder from `trainer.fault_plan`; SIGTERM delivery rides the
        ordinary callback hook."""
        trainer.fault_plan = self
        trainer.callbacks.append(self)
        return self

    def wrap_datamodule(self, datamodule: Any) -> Any:
        """Make `train_dataloader()` return fault-injecting loaders.
        The raise budget lives on the PLAN (shared dict), so it spans
        the several loader instances `fit` creates."""
        orig = datamodule.train_dataloader

        def wrapped():
            return FaultyLoader(orig(), self.loader_raise_at)

        datamodule.train_dataloader = wrapped
        return datamodule

    # -- trainer hook ---------------------------------------------------
    def on_train_step_end(self, trainer: Any, state: Any) -> None:
        t = self.sigterm_at_step
        if t is None:
            return
        prev = int(getattr(trainer, "prev_global_step",
                           trainer.global_step - 1))
        if prev < t <= trainer.global_step:
            self.sigterm_at_step = None
            self.fired.append(("sigterm", int(trainer.global_step)))
            os.kill(os.getpid(), signal.SIGTERM)

    def disarm_nan(self) -> None:
        self.fired.append(("nan_disarmed", sorted(self.nan_loss_at_steps)))
        self.nan_loss_at_steps = frozenset()


class FaultyLoader:
    """Loader wrapper raising `InjectedLoaderFault` at planned batches.

    `raise_at` maps a cumulative successful-batch index to the number
    of times pulling that batch fails; the dict is mutated in place so
    the budget is shared with the owning `FaultPlan` across loader
    re-creation. The raise happens BEFORE the underlying loader is
    advanced: a failed attempt consumes no samples.
    """

    def __init__(self, loader: Any, raise_at: dict):
        self.loader = loader
        self.raise_at = raise_at
        self._yielded = 0

    def __getattr__(self, name: str):
        return getattr(self.loader, name)

    def __len__(self) -> int:
        return len(self.loader)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def skip_next(self) -> None:
        """ResilientLoader's cooperative skip protocol: advance past the
        next (poison) batch without yielding it — delegating to the
        wrapped loader's own skip (which advances WITHOUT fetching)
        when it has one."""
        self._yielded += 1
        skip = getattr(self.loader, "skip_next", None)
        if callable(skip):
            skip()
        else:
            next(iter(self.loader), None)

    def __iter__(self):
        it = iter(self.loader)
        while True:
            idx = self._yielded
            if self.raise_at.get(idx, 0) > 0:
                self.raise_at[idx] -= 1
                raise InjectedLoaderFault(
                    f"injected loader fault at batch {idx}")
            try:
                batch = next(it)
            except StopIteration:
                return
            self._yielded += 1
            yield batch


def truncate_checkpoint_step(ckpt_path: str, step: int) -> list:
    """Corrupt a committed checkpoint step in place: remove the largest
    payload files under its directory (array data first). Returns the
    removed paths; raises if the step directory does not exist."""
    root = None
    for name in os.listdir(ckpt_path):
        full = os.path.join(ckpt_path, name)
        if os.path.isdir(full) and name.split(".")[0] == str(step):
            root = full
            break
    if root is None:
        raise FileNotFoundError(
            f"no step-{step} checkpoint directory under {ckpt_path}")
    files = []
    for dirpath, _, filenames in os.walk(root):
        for fn in filenames:
            p = os.path.join(dirpath, fn)
            files.append((os.path.getsize(p), p))
    if not files:
        raise FileNotFoundError(f"step-{step} checkpoint {root} is empty")
    files.sort(reverse=True)
    removed = []
    # the biggest files are the serialized arrays — removing them leaves
    # a committed-looking but unrestorable step
    for _, p in files[:max(1, len(files) // 2)]:
        os.remove(p)
        removed.append(p)
    return removed
