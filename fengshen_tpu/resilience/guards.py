"""In-graph step guards: skip non-finite / spiking optimizer updates.

At 10B-parameter, TB-dataset scale a bad microbatch (corrupt row, fp
overflow, a flaky interconnect read) is routine, and one NaN loss
poisons the parameters forever — the Megatron-LM-scale skip-bad-step
policy (https://arxiv.org/pdf/2104.04473 §B.2) made "drop the update,
keep the step" the standard answer. The guard here is computed INSIDE
the jitted step, so the no-fault path costs one finiteness reduction
and a `lax.cond` between two already-compiled branches — no host sync,
no extra dispatch (the acceptance bar of ISSUE 1: no measurable
regression on the fused train step).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def step_ok(metrics: dict, max_grad_norm: float = 0.0) -> jax.Array:
    """Boolean scalar: is this step's update safe to apply?

    Finite loss AND finite global grad norm; optionally also
    `grad_norm <= max_grad_norm` (spike guard) when a positive
    threshold is configured.
    """
    ok = jnp.isfinite(metrics["loss"]) & jnp.isfinite(metrics["grad_norm"])
    if max_grad_norm and max_grad_norm > 0:
        ok = ok & (metrics["grad_norm"] <= max_grad_norm)
    return ok


def guarded_apply(state, grads, ok: jax.Array):
    """Apply the optimizer update under `lax.cond(ok, ...)`.

    The bad branch advances `step` (LR schedule and host bookkeeping
    stay aligned with the good branch) and increments
    `bad_step_count`; params and optimizer moments are untouched, so a
    skipped step is exactly a no-op update.
    """
    def good(st):
        return st.apply_gradients(grads)

    def bad(st):
        return st.replace(step=st.step + 1,
                          bad_step_count=st.bad_step_count + 1)

    return jax.lax.cond(ok, good, bad, state)
