"""Taiyi-CLIP contrastive finetune on Flickr-style image-text CSVs.

Port of the reference workload
(reference: fengshen/examples/clip_finetune/clip_finetune_flickr.py):
the same contrastive module as pretrain_taiyi_clip with both towers
trainable and a finetune-scale LR — the reference splits pretrain/finetune
into separate dirs; here the finetune driver reuses the pretrain module.
"""

from __future__ import annotations


def main(argv=None):
    from fengshen_tpu.examples.pretrain_taiyi_clip.pretrain import main \
        as pretrain_main
    # finetune = same driver, both towers trainable (no --freeze_image_tower)
    pretrain_main(argv)


if __name__ == "__main__":
    main()
