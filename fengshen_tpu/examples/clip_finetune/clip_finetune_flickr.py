"""Taiyi-CLIP contrastive finetune on Flickr-style image-text CSVs.

Port of the reference workload
(reference: fengshen/examples/clip_finetune/clip_finetune_flickr.py):
the same contrastive module as pretrain_taiyi_clip with BOTH towers
trainable and the reference's finetune hyperparameters as defaults —
the per-vision-tower LR preset table (:184-196), AdamW betas
(0.9, 0.98) / eps 1e-6 for ViT, weight decay 0.2 (:198-206), and a
cosine schedule in place of its CosineAnnealingWarmRestarts (:210-213).
Any explicitly passed flag overrides the preset.
"""

from __future__ import annotations

import argparse

# reference :184-196 — LR by vision tower; Taiyi-CLIP ships ViT-B/32
CLIP_LR_PRESETS = {
    "RN50": 5e-4, "RN101": 5e-4, "RN50x4": 5e-4, "RN50x16": 4e-4,
    "RN50x64": 3.6e-4, "ViT-B/32": 5e-4, "ViT-B/16": 5e-4,
    "ViT-L/14": 4e-4, "ViT-L/14-336px": 2e-5,
}

def _finetune_defaults(clip_model: str) -> dict:
    is_vit = clip_model.startswith("ViT")
    return {
        "--weight_decay": "0.2",
        # reference :198-206: betas (0.9, 0.98) + eps 1e-6 for ViT
        # towers, (0.9, 0.999) + eps 1e-8 for the ResNet towers
        "--adam_beta2": "0.98" if is_vit else "0.999",
        "--adam_epsilon": "1e-6" if is_vit else "1e-8",
        "--scheduler_type": "cosine",
        "--learning_rate": str(CLIP_LR_PRESETS[clip_model]),
    }


def main(argv=None):
    import sys

    from fengshen_tpu.examples.pretrain_taiyi_clip.pretrain import main \
        as pretrain_main

    argv = list(sys.argv[1:] if argv is None else argv)
    peek = argparse.ArgumentParser(add_help=False)
    peek.add_argument("--clip_model", default="ViT-B/32",
                      choices=sorted(CLIP_LR_PRESETS))
    preset_args, argv = peek.parse_known_args(argv)

    # finetune = same driver, both towers trainable (no
    # --freeze_image_tower) with the reference finetune defaults; every
    # user-passed flag wins over a preset (both `--flag value` and
    # `--flag=value` forms count as passed)
    passed = {a.split("=", 1)[0] for a in argv if a.startswith("--")}
    for flag, value in _finetune_defaults(preset_args.clip_model).items():
        if flag not in passed:
            argv += [flag, value]
    pretrain_main(argv)


if __name__ == "__main__":
    main()
