"""TCBert topic-classification prompt demo.

Port of the reference driver (reference: fengshen/examples/tcbert/ —
TCBertPipelines prompt-based topic classification).
"""

from __future__ import annotations

import argparse

from fengshen_tpu.models.tcbert import TCBertPipelines


TEST_DATA = [{"content": "街头偶遇2018款长安CS35，颜值美炸！"},
             {"content": "今天股市大涨，投资者信心回升"}]
LABELS = ["汽车", "财经", "教育", "军事"]


def main(argv=None, pipeline=None):
    parser = argparse.ArgumentParser("TASK NAME")
    if hasattr(TCBertPipelines, "pipelines_args"):
        parser = TCBertPipelines.pipelines_args(parser)
    args, _ = parser.parse_known_args(argv)
    if pipeline is None:
        pipeline = TCBertPipelines(args,
                                   model=getattr(args, "model_path", None))
    result = pipeline.predict([s["content"] for s in TEST_DATA],
                              label_words=LABELS)
    for line in result:
        print(line)
    return result


if __name__ == "__main__":
    main()
