"""Taiyi Stable Diffusion Chinese txt2img demo.

Port of the reference demo (reference:
fengshen/examples/stable_diffusion_chinese/ — diffusers
StableDiffusionPipeline with the Taiyi Chinese text encoder): prompt →
classifier-free-guided DDPM sampling → image grid.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np


def main(argv=None, model=None, params=None, tokenizer=None,
         image_size=None, num_steps=None):
    from fengshen_tpu.models.stable_diffusion.sampling import text_to_image

    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", type=str, default=None)
    parser.add_argument("--prompt", type=str, default="飞流直下三千尺，油画")
    parser.add_argument("--negative_prompt", type=str, default="")
    parser.add_argument("--image_size", type=int, default=512)
    parser.add_argument("--num_steps", type=int, default=50)
    parser.add_argument("--guidance_scale", type=float, default=7.5)
    parser.add_argument("--out", type=str, default="out.png")
    args = parser.parse_args(argv)
    if image_size is not None:
        args.image_size = image_size
    if num_steps is not None:
        args.num_steps = num_steps

    if model is None:
        # demo-scale model when no checkpoint is given
        from fengshen_tpu.models.bert import BertConfig
        from fengshen_tpu.models.stable_diffusion.autoencoder_kl import (
            VAEConfig)
        from fengshen_tpu.models.stable_diffusion.modeling_taiyi_sd import (
            TaiyiStableDiffusion)
        from fengshen_tpu.models.stable_diffusion.unet import UNetConfig
        model = TaiyiStableDiffusion(
            BertConfig.small_test_config(), VAEConfig.small_test_config(),
            UNetConfig.small_test_config())
    if params is None:
        from fengshen_tpu.models.stable_diffusion.sampling import (
            init_sampling_params)
        params = init_sampling_params(model, jax.random.PRNGKey(0),
                                      args.image_size)

    if tokenizer is not None:
        ids = jnp.asarray([tokenizer.encode(args.prompt)], jnp.int32)
        neg = jnp.asarray([tokenizer.encode(args.negative_prompt or "")],
                          jnp.int32)
        if neg.shape[1] != ids.shape[1]:
            pad = tokenizer.pad_token_id or 0
            neg = jnp.full_like(ids, pad).at[:, :neg.shape[1]].set(
                neg[:, :ids.shape[1]])
    else:
        from fengshen_tpu.examples.demo_utils import toy_encode
        ids = jnp.asarray([toy_encode(args.prompt)], jnp.int32)
        neg = jnp.zeros_like(ids)

    images = text_to_image(model, params, ids, uncond_ids=neg,
                           image_size=args.image_size,
                           num_steps=args.num_steps,
                           guidance_scale=args.guidance_scale)
    arr = np.asarray(images[0])
    try:
        from PIL import Image
        Image.fromarray((arr * 255).astype(np.uint8)).save(args.out)
        print(f"saved {args.out}")
    except ImportError:
        pass
    return np.asarray(images)


if __name__ == "__main__":
    main()
