#!/bin/bash
# Launcher for pretrain_taiyi_clip.pretrain (reference pattern: fengshen/examples/pretrain_taiyi_clip/finetune.sh)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Taiyi-CLIP-Roberta-102M-Chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.pretrain_taiyi_clip.pretrain \
    --model_path $MODEL_PATH \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --train_csv $TRAIN_CSV --image_root $IMAGE_ROOT --freeze_image_tower
