#!/bin/bash
# hparams carried from reference: fengshen/examples/pretrain_taiyi_clip/test.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Taiyi-CLIP-RoBERTa-102M-ViT-L-Chinese}
python -m fengshen_tpu.examples.pretrain_taiyi_clip.pretrain \
    --model_path $MODEL_PATH \
    --test_only \
    --val_csv ${VAL_CSV:-flickr30k_cna_val.csv} \
    --image_root ${IMAGE_ROOT:-./images} \
    --default_root_dir $ROOT_DIR \
    --test_batchsize 64 \
    --log_every_n_steps 1 \
    --precision fp32
