"""Taiyi-CLIP contrastive pretraining (Chinese text tower + CLIP ViT).

Port of the reference workload
(reference: fengshen/examples/pretrain_taiyi_clip/pretrain.py): image-text
CSV data → CLIPCollator → symmetric InfoNCE over the in-batch similarity
matrix (clip_contrastive_loss), with the vision tower optionally frozen
(`--freeze_image_tower`, the reference's Chinese-adaptation recipe trains
only the text tower).
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax
import jax.numpy as jnp

from fengshen_tpu.data.clip_dataloader import CLIPCollator, ImageTextCSVDataset
from fengshen_tpu.models.bert import BertConfig
from fengshen_tpu.models.clip import (CLIPVisionConfig, TaiyiCLIPModel,
                                      clip_contrastive_loss)
from fengshen_tpu.trainer.module import TrainModule


class TaiyiCLIPModule(TrainModule):
    """reference: pretrain_taiyi_clip/pretrain.py contrastive module."""

    def __init__(self, args, text_config: Optional[BertConfig] = None,
                 vision_config: Optional[CLIPVisionConfig] = None):
        super().__init__(args)
        if text_config is None and getattr(args, "model_path", None):
            text_config = BertConfig.from_pretrained(args.model_path)
        self.text_config = text_config
        self.vision_config = vision_config or CLIPVisionConfig()
        self.model = TaiyiCLIPModel(text_config, self.vision_config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("taiyi clip")
        parser.add_argument("--image_size", type=int, default=224)
        parser.add_argument("--max_length", type=int, default=77)
        parser.add_argument("--freeze_image_tower", action="store_true",
                            default=False)
        parser.add_argument("--train_csv", type=str, default=None)
        parser.add_argument("--image_root", type=str, default=None)
        return parent_parser

    def init_params(self, rng):
        size = self.vision_config.image_size
        ids = jnp.zeros((1, 8), jnp.int32)
        pixels = jnp.zeros((1, size, size, 3), jnp.float32)
        return self.model.init(rng, ids, pixels)["params"]

    def training_loss(self, params, batch, rng):
        if getattr(self.args, "freeze_image_tower", False):
            # stop grads into the vision tower (reference freezes it and
            # trains the Chinese text tower only)
            params = dict(params)
            for key in list(params):
                if key.startswith(("vision", "visual")):
                    params[key] = jax.lax.stop_gradient(params[key])
        text_emb, image_emb, scale = self.model.apply(
            {"params": params}, batch["input_ids"], batch["pixel_values"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, logits = clip_contrastive_loss(text_emb, image_emb, scale)
        labels = jnp.arange(logits.shape[0])
        acc = (logits.argmax(1) == labels).mean()
        return loss, {"acc": acc, "logit_scale": scale}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = TaiyiCLIPModule.add_module_specific_args(parser)
    # reference: pretrain_taiyi_clip/test.sh — eval-only retrieval pass
    parser.add_argument("--test_only", action="store_true", default=False)
    parser.add_argument("--val_csv", type=str, default=None)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    datasets = {}
    if args.train_csv:
        datasets["train"] = ImageTextCSVDataset(args.train_csv,
                                                image_root=args.image_root)
    if args.val_csv:
        datasets["validation"] = ImageTextCSVDataset(
            args.val_csv, image_root=args.image_root)
    collator = CLIPCollator(tokenizer, image_size=args.image_size,
                            max_length=args.max_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args,
                                     datasets=datasets or None)
    module = TaiyiCLIPModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    if args.test_only:
        trainer.validate(module, datamodule)
    else:
        trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
