#!/bin/bash
# Launcher for clue_sim.finetune_clue_sim (reference pattern: fengshen/examples/clue_sim/main.py)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-MegatronBert-1.3B}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.clue_sim.finetune_clue_sim \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-2e-5} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --num_labels 3 --loss_function lsce
