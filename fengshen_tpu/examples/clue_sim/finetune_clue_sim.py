"""QBQTC (CLUE semantic-similarity) finetune.

Port of the reference workload
(reference: fengshen/examples/clue_sim/finetune_clue_sim.py:30-260 +
loss.py:19-60): {query, title, label∈{0,1,2}} pairs classified with a
BERT-family pair encoder, trained with CE / focal / label-smoothing losses
(--loss_function, the reference's ablation surface).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.megatron_bert import (
    MegatronBertConfig, MegatronBertForSequenceClassification)
from fengshen_tpu.trainer.module import TrainModule


def focal_loss(logits, labels, gamma: float = 2.0):
    """Multi-class focal loss (reference: loss.py:19-40)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    gold = jnp.take_along_axis(logp, labels[:, None], 1)[:, 0]
    p = jnp.exp(gold)
    return (-((1 - p) ** gamma) * gold).mean()


def label_smoothing_ce(logits, labels, eps: float = 0.1):
    """Label-smoothing CE (reference: loss.py:42-60)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    gold = jnp.take_along_axis(logp, labels[:, None], 1)[:, 0]
    return (-(1 - eps) * gold - eps * logp.mean(-1)).mean()


@dataclass
class ClueSimCollator:
    """query/title pair → [CLS] q [SEP] t [SEP]
    (reference: finetune_clue_sim.py:30-80)."""

    tokenizer: Any
    max_seq_length: int = 128

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        pad_id = tok.pad_token_id or 0
        max_len = self.max_seq_length
        batch = {"input_ids": [], "attention_mask": [],
                 "token_type_ids": [], "labels": []}
        for s in samples:
            q = tok.encode(s["query"], add_special_tokens=False)
            t = tok.encode(s["title"], add_special_tokens=False)
            avail = max_len - 3
            q = q[: avail // 2]
            t = t[: avail - len(q)]
            ids = [tok.cls_token_id] + q + [tok.sep_token_id] + t + \
                [tok.sep_token_id]
            tt = [0] * (len(q) + 2) + [1] * (len(t) + 1)
            pad = max_len - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["token_type_ids"].append(tt + [0] * pad)
            batch["labels"].append(int(s.get("label", 0)))
        return {k: np.asarray(v) for k, v in batch.items()}


class ClueSimModule(TrainModule):
    def __init__(self, args, config: Optional[MegatronBertConfig] = None):
        super().__init__(args)
        import dataclasses as dc
        if config is None and getattr(args, "model_path", None):
            config = MegatronBertConfig.from_pretrained(args.model_path)
        if config is None:
            raise ValueError("ClueSimModule needs a config or --model_path")
        config = dc.replace(config, num_labels=args.num_labels)
        self.config = config
        self.model = MegatronBertForSequenceClassification(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("clue_sim")
        parser.add_argument("--max_seq_length", type=int, default=128)
        parser.add_argument("--num_labels", type=int, default=3)
        parser.add_argument("--loss_function", type=str, default="ce",
                            choices=["ce", "focal", "lsce"])
        return parent_parser

    def init_params(self, rng):
        ids = jnp.zeros((1, 16), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"],
            deterministic=False, rngs={"dropout": rng})
        kind = getattr(self.args, "loss_function", "ce")
        if kind == "focal":
            loss = focal_loss(logits, batch["labels"])
        elif kind == "lsce":
            loss = label_smoothing_ce(logits, batch["labels"])
        else:
            from fengshen_tpu.parallel.cross_entropy import (
                stable_cross_entropy)
            loss, _ = stable_cross_entropy(logits[:, None, :],
                                           batch["labels"][:, None])
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        return loss, {"acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = ClueSimModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = ClueSimCollator(tokenizer,
                               max_seq_length=args.max_seq_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = ClueSimModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
