"""PPVAE conditional-generation demo: train the plug-in bottleneck on
condition-positive latents, then decode bottleneck noise to text
(reference: fengshen/examples/PPVAE/generate.py)."""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.davae import DAVAEModel
from fengshen_tpu.models.ppvae import PPVAEConfig, PPVAEModel


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=4)
    parser.add_argument("--plugin_steps", type=int, default=50)
    parser.add_argument("--max_length", type=int, default=12)
    args = parser.parse_args(argv)

    cfg = PPVAEConfig.small_test_config()
    vae = DAVAEModel(cfg.vae)
    vae_params = vae.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    ppvae = PPVAEModel(cfg, vae_model=vae, vae_params=vae_params)

    rng = np.random.RandomState(0)
    pos = jnp.asarray(rng.randn(16, cfg.latent_dim) * 0.2 + 1.5,
                      jnp.float32)
    loss, metrics = ppvae.train_plugin(pos, steps=args.plugin_steps)
    print(f"plugin trained: loss={loss:.4f} kl={metrics['pos_kl']:.4f}")
    out = ppvae.generate(args.n, max_length=args.max_length)
    for row in np.asarray(out):
        print(" ".join(str(int(t)) for t in row))
    return np.asarray(out)


if __name__ == "__main__":
    main()
