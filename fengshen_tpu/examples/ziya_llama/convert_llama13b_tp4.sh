#!/bin/bash
# hparams carried from reference: fengshen/examples/ziya_llama/convert_llama13b_tp4.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
# TP=4 intent: validates divisibility and records it; the checkpoint
# stays logical (load-time resharding makes offline TP splits obsolete).
python -m fengshen_tpu.models.llama.convert \
    --input_path ${INPUT_DIR:-llama13b_hf} \
    --output_path ${OUTPUT_DIR:-llama13b_fs_tp4} \
    --model_parallel_size 4
