#!/bin/bash
# hparams carried from reference: fengshen/examples/ziya_llama/convert_llama13b_to_fs.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
# HF llama -> ONE logical fengshen-tpu checkpoint (orbax); no per-rank
# part_i dirs: TP sharding happens at load time from partition rules.
python -m fengshen_tpu.models.llama.convert \
    --input_path ${INPUT_DIR:-llama13b_hf} \
    --output_path ${OUTPUT_DIR:-llama13b_fs}
