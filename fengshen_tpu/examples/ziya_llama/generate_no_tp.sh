#!/bin/bash
# hparams carried from reference: fengshen/examples/ziya_llama/generate_no_tp.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-./llama13b_fs}
python -m fengshen_tpu.examples.ziya_inference.generate_ziya \
    --model_path $MODEL_PATH \
    --query "${QUERY:-帮我写一份去西安的旅游计划}" \
    --max_new_tokens 128 \
    --temperature 0.8 --top_p 0.85
