#!/bin/bash
# Ziya-LLaMA SFT launcher — the TPU counterpart of the reference's
# finetune_with_tp.sh (reference: fengshen/examples/ziya_llama/
# finetune_with_tp.sh: SLURM srun + heredoc DeepSpeed JSON + TP=8).
# Here the whole DeepSpeed/NCCL surface is four mesh flags; run one process
# per HOST (not per chip) — jax.distributed handles the rest.

MODEL_PATH=${MODEL_PATH:-"./ziya-llama-13b"}
TRAIN_FILE=${TRAIN_FILE:-"./sft_train.jsonl"}
OUTPUT=${OUTPUT:-"./runs/ziya_sft"}

python -m fengshen_tpu.examples.ziya_llama.finetune_ziya_llama \
    --model_path "$MODEL_PATH" \
    --train_file "$TRAIN_FILE" \
    --max_seq_length 1024 \
    --train_batchsize 1 \
    --accumulate_grad_batches 8 \
    --tensor_model_parallel_size 8 \
    --fsdp_parallel_size 1 \
    --learning_rate 1e-5 \
    --warmup_ratio 0.03 \
    --scheduler_type cosine \
    --max_epochs 2 \
    --precision bf16 \
    --gradient_clip_val 1.0 \
    --every_n_train_steps 500 \
    --save_ckpt_path "$OUTPUT/ckpt" \
    --load_ckpt_path "$OUTPUT/ckpt" \
    --default_root_dir "$OUTPUT"
