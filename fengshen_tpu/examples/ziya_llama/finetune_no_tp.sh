#!/bin/bash
# hparams carried from reference: fengshen/examples/ziya_llama/finetune_no_tp.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-./llama13b_fs}
python -m fengshen_tpu.examples.ziya_llama.finetune_ziya_llama \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-./data/small_train.json} \
    --val_file ${VAL_FILE:-./data/small_valid.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt_no_tp --save_last \
    --every_n_train_steps 100 \
    --train_batchsize 2 --val_batchsize 2 \
    --max_seq_length 256 \
    --learning_rate 1e-4 --min_learning_rate 1e-5 \
    --weight_decay 0.1 --warmup_ratio 0.05 \
    --adam_beta1 0.9 --adam_beta2 0.95 \
    --fsdp_parallel_size 8 \
    --max_epochs 4 --log_every_n_steps 1 \
    --precision bf16
