"""Ziya-LLaMA SFT — the reference's north-star tensor-parallel workload.

Port of reference: fengshen/examples/ziya_llama/finetune_ziya_llama.py:
the LlamaSFTCollator ("<human>:" / "<bot>:" prompt format, -100-masked
prompt labels, right padding, :35-85), the Llama LightningModule
(:98-182), and the argparse composition (:185-230). The reference's
DeepSpeedStrategy(tensor_model_parallel_size=8) + per-rank `part_{i}` shard
dirs become mesh flags + one logical checkpoint resharded at load.

Run (training):
    python -m fengshen_tpu.examples.ziya_llama.finetune_ziya_llama \
        --model_path <hf-llama-dir> --train_file sft.json \
        --tensor_model_parallel_size 8 --max_seq_length 1024 ...
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.trainer.modules import CausalLMModule


@dataclass
class LlamaSFTCollator:
    """Reference: finetune_ziya_llama.py:35-85 — prompt
    '<human>:{q}\\n<bot>:{a}', prompt tokens label-masked to -100,
    right-padded to max_seq_length."""

    tokenizer: Any
    max_seq_length: int = 1024
    prompt_key: str = "query"
    answer_key: str = "answer"

    def __call__(self, samples: list[dict]) -> dict:
        batch = {"input_ids": [], "attention_mask": [], "labels": []}
        pad_id = self.tokenizer.pad_token_id or 0
        eos_id = self.tokenizer.eos_token_id
        for s in samples:
            prompt = f"<human>:{s[self.prompt_key].strip()}\n<bot>:"
            prompt_ids = self.tokenizer.encode(prompt)
            answer_ids = self.tokenizer.encode(
                s[self.answer_key], add_special_tokens=False)
            if eos_id is not None:
                answer_ids = answer_ids + [eos_id]
            ids = (prompt_ids + answer_ids)[: self.max_seq_length]
            labels = ([-100] * len(prompt_ids) + answer_ids)[
                : self.max_seq_length]
            pad = self.max_seq_length - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["labels"].append(labels + [-100] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


@dataclass
class LlamaSFTPackedCollator:
    """Sequence-packing variant of `LlamaSFTCollator` (beyond-reference:
    the flash kernel's segment-id support makes packing free, so short
    SFT samples stop wasting pad FLOPs).

    Greedily packs samples into rows of `max_seq_length`. Emits
    `attention_mask` holding per-example SEGMENT IDS (1..n per row,
    0 = pad) and `position_ids` restarting at 0 per example — the
    contract of `LlamaConfig.packed_sequences=True`. Loss semantics are
    identical to the padded collator: prompt tokens and pads are -100,
    and the cross-example shift position lands on the next example's
    prompt start (always -100), so no token leaks across examples.

    `fixed_rows` pins the output row count (all-pad filler rows added,
    overflow rows dropped) so every batch has the same shape — variable
    shapes would retrigger XLA compilation per step on TPU.
    """

    tokenizer: Any
    max_seq_length: int = 1024
    prompt_key: str = "query"
    answer_key: str = "answer"
    fixed_rows: Any = None

    def _encode(self, s: dict) -> tuple[list, list]:
        eos_id = self.tokenizer.eos_token_id
        prompt = f"<human>:{s[self.prompt_key].strip()}\n<bot>:"
        prompt_ids = self.tokenizer.encode(prompt)
        answer_ids = self.tokenizer.encode(
            s[self.answer_key], add_special_tokens=False)
        if eos_id is not None:
            answer_ids = answer_ids + [eos_id]
        ids = (prompt_ids + answer_ids)[: self.max_seq_length]
        labels = ([-100] * len(prompt_ids) + answer_ids)[
            : self.max_seq_length]
        return ids, labels

    def __call__(self, samples: list[dict]) -> dict:
        pad_id = self.tokenizer.pad_token_id or 0
        rows, cur = [], {"ids": [], "labels": [], "segs": [], "pos": []}
        seg = 1
        for s in samples:
            ids, labels = self._encode(s)
            if cur["ids"] and \
                    len(cur["ids"]) + len(ids) > self.max_seq_length:
                rows.append(cur)
                cur = {"ids": [], "labels": [], "segs": [], "pos": []}
                seg = 1
            cur["ids"] += ids
            cur["labels"] += labels
            cur["segs"] += [seg] * len(ids)
            cur["pos"] += list(range(len(ids)))
            seg += 1
        if cur["ids"]:
            rows.append(cur)
        if self.fixed_rows is not None:
            if len(rows) > self.fixed_rows:
                # silent truncation is training-data loss — count it so a
                # mis-sized --packed_rows is visible in the logs
                prev = getattr(self, "dropped_rows", 0)
                self.dropped_rows = prev + len(rows) - self.fixed_rows
                # warn on the first drop and every 100-row threshold
                if prev == 0 or prev // 100 != self.dropped_rows // 100:
                    import logging
                    logging.getLogger("fengshen_tpu").warning(
                        "[packed] dropped %d overflow row(s) so far — "
                        "batches pack into more than --packed_rows=%d "
                        "rows; raise it to keep all data",
                        self.dropped_rows, self.fixed_rows)
            rows = rows[: self.fixed_rows]
            empty = {"ids": [], "labels": [], "segs": [], "pos": []}
            rows += [empty] * (self.fixed_rows - len(rows))

        batch = {"input_ids": [], "attention_mask": [], "labels": [],
                 "position_ids": []}
        for r in rows:
            pad = self.max_seq_length - len(r["ids"])
            batch["input_ids"].append(r["ids"] + [pad_id] * pad)
            batch["attention_mask"].append(r["segs"] + [0] * pad)
            batch["labels"].append(r["labels"] + [-100] * pad)
            batch["position_ids"].append(r["pos"] + [0] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


class Llama(CausalLMModule):
    """Reference: finetune_ziya_llama.py:98-182."""

    def __init__(self, args, config: Optional[LlamaConfig] = None):
        if config is None and getattr(args, "model_path", None):
            config = LlamaConfig.from_pretrained(args.model_path)
        model = LlamaForCausalLM(config)
        super().__init__(args, model, config)
        self._pretrained_params = None

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("Ziya Llama")
        parser.add_argument("--max_seq_length", type=int, default=1024)
        parser.add_argument("--prompt_key", type=str, default="query")
        parser.add_argument("--answer_key", type=str, default="answer")
        parser.add_argument("--packed", action="store_true",
                            help="sequence-pack SFT samples (segment-id "
                                 "attention; no pad FLOPs)")
        parser.add_argument("--packed_rows", type=int, default=None,
                            help="fixed packed-row count per batch "
                                 "(static shapes for TPU jit)")
        parser.add_argument(
            "--offload_params", action="store_true", default=False,
            help="ZeRO-3 analog: params + adam moments live in host "
                 "memory and stream to HBM one decoder layer at a time "
                 "(trainer/param_streaming.py) — for models whose "
                 "params+moments dwarf one chip's HBM (the 13B "
                 "finetune). Incompatible with --packed.")
        from fengshen_tpu.trainer.modules import add_lora_args
        add_lora_args(parser,
                      targets_default=r"(q_proj|k_proj|v_proj|o_proj)")
        parser.add_argument(
            "--offload_moments_dtype", default="param", type=str,
            choices=["param", "auto", "float32", "bfloat16"],
            help="host-resident adam moment storage dtype under "
                 "--offload_params. 'param' (default) = bit-parity "
                 "with the monolithic optax step; 'auto' lets the "
                 "offload policy pick bfloat16 when fp32 moments "
                 "would exceed half of host RAM (docs/offload.md); "
                 "'bfloat16' halves "
                 "the moment memory (fp32 m+v for 13B is 104 GB — "
                 "more than many hosts; bf16 is 52 GB) with update "
                 "math in fp32. fp16 is deliberately NOT offered "
                 "(second-moment underflow diverges).")
        return parent_parser

    def setup(self, stage: str = "fit") -> None:
        """Load pretrained HF weights once (replaces the reference's
        per-TP-rank `part_{i}` dirs, finetune_ziya_llama.py:102-107)."""
        path = getattr(self.args, "model_path", None)
        if path:
            import os
            if any(os.path.exists(os.path.join(path, f))
                   for f in ("pytorch_model.bin", "model.safetensors",
                             "pytorch_model.bin.index.json",
                             "model.safetensors.index.json")):
                from fengshen_tpu.models.llama.convert import (
                    load_hf_pretrained)
                _, self._pretrained_params = load_hf_pretrained(
                    path, self.config)

    def init_params(self, rng):
        if self._pretrained_params is not None:
            import jax.numpy as jnp
            dtype = jnp.dtype(self.config.param_dtype)
            return jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, dtype), self._pretrained_params)
        return super().init_params(rng)

    def predict_step(self, params, batch, rng=None, **gen_kwargs):
        """Reference: finetune_ziya_llama.py:155-176 → llama_generate."""
        from fengshen_tpu.utils.generate import generate
        return generate(self.model, params, batch["input_ids"],
                        attention_mask=batch.get("attention_mask"),
                        eos_token_id=self.config.eos_token_id,
                        pad_token_id=self.config.pad_token_id,
                        rng=rng, **gen_kwargs)


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = Llama.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    if args.packed:
        # static shapes are mandatory under jit: derive a row count when
        # none is given (assume ~2× packing; overflow rows are dropped)
        rows = args.packed_rows or max(1, args.train_batchsize // 2)
        collator = LlamaSFTPackedCollator(
            tokenizer, max_seq_length=args.max_seq_length,
            prompt_key=args.prompt_key, answer_key=args.answer_key,
            fixed_rows=rows)
    else:
        collator = LlamaSFTCollator(tokenizer,
                                    max_seq_length=args.max_seq_length,
                                    prompt_key=args.prompt_key,
                                    answer_key=args.answer_key)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = Llama(args)
    if args.packed:
        module.config.packed_sequences = True
    from fengshen_tpu.trainer.modules import maybe_wrap_lora
    module = maybe_wrap_lora(module, args)
    # Trainer.__init__ installs the process-global mesh the datamodule's
    # DP sharding reads — load-bearing in BOTH branches
    trainer = Trainer(args)
    ckpt = UniversalCheckpoint(args)
    if getattr(args, "offload_params", False):
        if args.packed:
            raise ValueError("--offload_params streams per-layer with "
                             "default positions; use unpacked batches")
        import jax
        import optax

        from fengshen_tpu.trainer.param_streaming import (
            llama_stream_spec, run_streamed_fit)
        from fengshen_tpu.trainer.train_state import TrainState

        module.setup("fit")
        params = module.init_params(jax.random.PRNGKey(
            getattr(args, "seed", 42)))
        # resume: restore weights before the engine takes the host
        # master copies (streamed checkpoints are weights-only)
        state0 = TrainState.create(apply_fn=module.model.apply,
                                   params=params, tx=optax.set_to_zero())
        class _View:  # maybe_restore records the restored step here
            global_step = 0
            consumed_samples = 0
        state0 = ckpt.maybe_restore(state0, _View(), weights_only=True)
        spec = llama_stream_spec(module.config, state0.params)
        del params, state0

        def log(step, loss, metrics, peak):
            print(f"[streamed] step={step} loss={loss:.4f} "
                  f"grad_norm={metrics.get('grad_norm', 0):.3g} "
                  f"peak_hbm_gb={peak / 1e9:.2f}", flush=True)

        # no device park: the streamed models are the ones whose params
        # dwarf one chip's HBM
        run_streamed_fit(args, spec, datamodule.train_dataloader(),
                         module.model.apply, ckpt=ckpt, log=log)
    else:
        trainer.callbacks.append(ckpt)
        trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
