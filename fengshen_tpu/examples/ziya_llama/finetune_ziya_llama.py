"""Ziya-LLaMA SFT — the reference's north-star tensor-parallel workload.

Port of reference: fengshen/examples/ziya_llama/finetune_ziya_llama.py:
the LlamaSFTCollator ("<human>:" / "<bot>:" prompt format, -100-masked
prompt labels, right padding, :35-85), the Llama LightningModule
(:98-182), and the argparse composition (:185-230). The reference's
DeepSpeedStrategy(tensor_model_parallel_size=8) + per-rank `part_{i}` shard
dirs become mesh flags + one logical checkpoint resharded at load.

Run (training):
    python -m fengshen_tpu.examples.ziya_llama.finetune_ziya_llama \
        --model_path <hf-llama-dir> --train_file sft.json \
        --tensor_model_parallel_size 8 --max_seq_length 1024 ...
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.trainer.modules import CausalLMModule


@dataclass
class LlamaSFTCollator:
    """Reference: finetune_ziya_llama.py:35-85 — prompt
    '<human>:{q}\\n<bot>:{a}', prompt tokens label-masked to -100,
    right-padded to max_seq_length."""

    tokenizer: Any
    max_seq_length: int = 1024
    prompt_key: str = "query"
    answer_key: str = "answer"

    def __call__(self, samples: list[dict]) -> dict:
        batch = {"input_ids": [], "attention_mask": [], "labels": []}
        pad_id = self.tokenizer.pad_token_id or 0
        eos_id = self.tokenizer.eos_token_id
        for s in samples:
            prompt = f"<human>:{s[self.prompt_key].strip()}\n<bot>:"
            prompt_ids = self.tokenizer.encode(prompt)
            answer_ids = self.tokenizer.encode(
                s[self.answer_key], add_special_tokens=False)
            if eos_id is not None:
                answer_ids = answer_ids + [eos_id]
            ids = (prompt_ids + answer_ids)[: self.max_seq_length]
            labels = ([-100] * len(prompt_ids) + answer_ids)[
                : self.max_seq_length]
            pad = self.max_seq_length - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["labels"].append(labels + [-100] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


class Llama(CausalLMModule):
    """Reference: finetune_ziya_llama.py:98-182."""

    def __init__(self, args, config: Optional[LlamaConfig] = None):
        if config is None and getattr(args, "model_path", None):
            config = LlamaConfig.from_pretrained(args.model_path)
        model = LlamaForCausalLM(config)
        super().__init__(args, model, config)
        self._pretrained_params = None

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("Ziya Llama")
        parser.add_argument("--max_seq_length", type=int, default=1024)
        parser.add_argument("--prompt_key", type=str, default="query")
        parser.add_argument("--answer_key", type=str, default="answer")
        return parent_parser

    def setup(self, stage: str = "fit") -> None:
        """Load pretrained HF weights once (replaces the reference's
        per-TP-rank `part_{i}` dirs, finetune_ziya_llama.py:102-107)."""
        path = getattr(self.args, "model_path", None)
        if path:
            import os
            if any(os.path.exists(os.path.join(path, f))
                   for f in ("pytorch_model.bin", "model.safetensors",
                             "pytorch_model.bin.index.json",
                             "model.safetensors.index.json")):
                from fengshen_tpu.models.llama.convert import (
                    load_hf_pretrained)
                _, self._pretrained_params = load_hf_pretrained(
                    path, self.config)

    def init_params(self, rng):
        if self._pretrained_params is not None:
            import jax.numpy as jnp
            dtype = jnp.dtype(self.config.param_dtype)
            return jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, dtype), self._pretrained_params)
        return super().init_params(rng)

    def predict_step(self, params, batch, rng=None, **gen_kwargs):
        """Reference: finetune_ziya_llama.py:155-176 → llama_generate."""
        from fengshen_tpu.utils.generate import generate
        return generate(self.model, params, batch["input_ids"],
                        attention_mask=batch.get("attention_mask"),
                        eos_token_id=self.config.eos_token_id,
                        pad_token_id=self.config.pad_token_id,
                        rng=rng, **gen_kwargs)


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = Llama.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = LlamaSFTCollator(tokenizer,
                                max_seq_length=args.max_seq_length,
                                prompt_key=args.prompt_key,
                                answer_key=args.answer_key)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = Llama(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
