"""Della (deepVAE) pretraining.

Port of the reference workload
(reference: fengshen/examples/deepVAE/pretrain_deep_vae.py): hierarchical
per-layer-latent VAE training with KL annealing (beta warmup).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.deepvae import DellaConfig, DellaModel, della_loss
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class TextLMCollator:
    tokenizer: Any
    max_seq_length: int = 128
    content_key: str = "text"

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        pad_id = tok.pad_token_id or 0
        batch = {"input_ids": [], "attention_mask": []}
        for s in samples:
            ids = tok.encode(s[self.content_key], add_special_tokens=False
                             )[: self.max_seq_length]
            pad = self.max_seq_length - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


class DellaPretrainModule(TrainModule):
    def __init__(self, args, config: Optional[DellaConfig] = None):
        super().__init__(args)
        self.config = config or DellaConfig()
        self.model = DellaModel(self.config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("della pretrain")
        parser.add_argument("--max_seq_length", type=int, default=128)
        parser.add_argument(
            "--kl_weight", type=float, default=1.0,
            help="constant KL weight; pair with --free_bits for the "
                 "posterior-collapse mitigation")
        parser.add_argument("--free_bits", type=float, default=0.0)
        return parent_parser

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        rng, sample_rng, drop_rng = jax.random.split(rng, 3)
        logits, posts, priors = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            rng=sample_rng, deterministic=False,
            rngs={"dropout": drop_rng})
        loss, metrics = della_loss(
            logits, batch["input_ids"], posts, priors,
            kl_weight=getattr(self.args, "kl_weight", 1.0),
            free_bits=getattr(self.args, "free_bits", 0.0))
        return loss, metrics

    def partition_rules(self):
        return super().partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = DellaPretrainModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = TextLMCollator(tokenizer,
                              max_seq_length=args.max_seq_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = DellaPretrainModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
