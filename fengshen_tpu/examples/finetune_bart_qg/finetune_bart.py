"""Randeng-BART question generation (ChineseSQuAD) finetune.

Port of the reference workload
(reference: fengshen/examples/finetune_bart_qg/finetune_bart.py:40-429):
answer-aware question generation — the context is encoded with the answer
span masked according to `--mask_ans_style` (normal → replace the answer
with the mask token; unmask → keep; anstoken → a dedicated <ans> marker,
reference: finetune_bart.py:93-130), concatenated with the answer, and BART
generates the question.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.examples.summary.seq2seq_summary import Seq2SeqCollator
from fengshen_tpu.models.bart import BartConfig, BartForConditionalGeneration
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class BartQGCollator(Seq2SeqCollator):
    """{context, answer, ans_span, question} → seq2seq sample
    (reference: finetune_bart.py:60-140). Batching (truncate/eos/shift/pad
    and the checkpoint's decoder_start_token_id) comes from
    Seq2SeqCollator; only the answer-masked source construction lives
    here."""

    mask_ans_style: str = "anstoken"
    ans_token: str = "<ans>"

    def mask_context(self, sample: dict) -> str:
        """reference: finetune_bart.py:93-130."""
        context = sample["context"]
        if self.mask_ans_style == "unmask":
            return context
        answer = sample["answer"][0] if isinstance(sample["answer"], list) \
            else sample["answer"]
        if self.mask_ans_style == "normal":
            token = self.tokenizer.mask_token or self.ans_token
        else:  # anstoken
            token = self.ans_token
        span = sample.get("ans_span")
        if span:
            bos, eos = span[0] if isinstance(span[0], (list, tuple)) else span
            return context[:bos] + token + context[eos:]
        return context.replace(answer, token, 1)

    def source_text(self, sample: dict) -> str:
        answer = sample["answer"][0] if isinstance(sample["answer"], list) \
            else sample["answer"]
        sep = self.tokenizer.sep_token or ""
        return self.mask_context(sample) + sep + answer

    def target_text(self, sample: dict) -> str:
        return sample["question"]


class BartQGModule(TrainModule):
    """Seq2seq QG loss (reference: finetune_bart.py BARTFinetuneModel)."""

    def __init__(self, args, config: Optional[BartConfig] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = BartConfig.from_pretrained(args.model_path)
        self.config = config
        self.model = BartForConditionalGeneration(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("BART QG")
        parser.add_argument("--max_seq_length", type=int, default=512)
        parser.add_argument("--max_target_length", type=int, default=64)
        parser.add_argument(
            "--mask_ans_style", default="anstoken", type=str,
            choices=["normal", "unmask", "anstoken"])
        return parent_parser

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            batch["decoder_input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits,
                                                      batch["labels"])
        return loss, {"n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = BartQGModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    module = BartQGModule(args)
    collator = BartQGCollator(
        tokenizer, max_src_length=args.max_seq_length,
        max_tgt_length=args.max_target_length,
        decoder_start_token_id=module.config.decoder_start_token_id,
        mask_ans_style=args.mask_ans_style)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
