#!/bin/bash
# Launcher for finetune_bart_qg.finetune_bart (reference pattern: fengshen/examples/finetune_bart_qg/finetune_bart.sh)
# Multi-host TPU: run this script on every host with JAX_COORDINATOR_ADDRESS
# set (see docs/multihost.md); single host needs no extra flags.
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-BART-139M-QG-Chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/finetune_bart_qg.finetune_bart}

python -m fengshen_tpu.examples.finetune_bart_qg.finetune_bart \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-32} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --mask_ans_style anstoken
