#!/bin/bash
# hparams carried from reference: fengshen/examples/qa_t5/run_predict.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-T5-784M-QA-Chinese}
python -m fengshen_tpu.examples.qa_t5.finetune_t5_cmrc \
    --pretrained_model_path $MODEL_PATH \
    --test_file ${TEST_FILE:-test.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --do_eval_only \
    --prediction_res_path $ROOT_DIR/predictions_sampling.txt \
    --val_batchsize 8 --test_batchsize 8 \
    --max_seq_length 512 \
    --precision bf16
