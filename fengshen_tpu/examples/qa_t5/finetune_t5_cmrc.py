"""Randeng-T5 QA finetune on CMRC-style extractive/generative QA.

Port of the reference workload
(reference: fengshen/examples/qa_t5/finetune_t5_cmrc.py:1-450 +
qa_dataset.py:36-187): samples with question/context/answer are formatted as
``question:{q}knowledge:{context}`` → ``<extra_id_0>{answer}`` (the
reference's prompt scheme, qa_dataset.py:44-76) and trained with the
seq2seq CE; prediction decodes with the scan-based sampler.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.examples.summary.seq2seq_summary import Seq2SeqCollator
from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class T5QACollator(Seq2SeqCollator):
    """question/context/answer → prompt + target
    (reference: qa_dataset.py:36-110); batching inherited from
    Seq2SeqCollator, only the prompt formatting here."""

    max_knowledge_length: int = 425

    def source_text(self, sample: dict) -> str:
        return ("question:" + sample["question"] +
                "knowledge:" + sample["context"][: self.max_knowledge_length])

    def target_text(self, sample: dict) -> str:
        answer = sample["answer"][0] if isinstance(sample["answer"], list) \
            else sample["answer"]
        return "<extra_id_0>" + answer


class T5QAModule(TrainModule):
    """Seq2seq QA loss (reference: finetune_t5_cmrc.py QAFinetuneModel)."""

    def __init__(self, args, config: Optional[T5Config] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = T5Config.from_pretrained(args.model_path)
        self.config = config
        self.model = T5ForConditionalGeneration(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("T5 QA")
        parser.add_argument("--max_seq_length", type=int, default=512)
        parser.add_argument("--max_knowledge_length", type=int, default=425)
        parser.add_argument("--max_target_length", type=int, default=64)
        parser.add_argument("--num_beams", type=int, default=4)
        parser.add_argument("--length_penalty", type=float, default=1.0)
        parser.add_argument("--repetition_penalty", type=float,
                            default=1.0)
        parser.add_argument("--no_repeat_ngram_size", type=int,
                            default=0)
        parser.add_argument("--min_length", type=int, default=0)
        return parent_parser

    jit_predict = True

    def predict_step(self, params, batch):
        """Beam-search decode (reference: finetune_t5_cmrc.py:217-224
        decodes with `model.generate(num_beams=4|10)`)."""
        from fengshen_tpu.utils.generate import seq2seq_predict_step
        return seq2seq_predict_step(
            self.model, self.config, self.args, params, batch,
            max_new_tokens=self.args.max_target_length)

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            batch["decoder_input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits,
                                                      batch["labels"])
        return loss, {"n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = T5QAModule.add_module_specific_args(parser)
    # reference: qa_t5/run_predict.sh — eval-only decode of the test
    # split into a text file
    group = parser.add_argument_group("qa predict")
    group.add_argument("--do_eval_only", action="store_true",
                       default=False)
    group.add_argument("--pretrained_model_path", default=None, type=str,
                       help="alias of --model_path (reference flag name)")
    group.add_argument("--prediction_res_path",
                       default="./predictions.txt", type=str)
    args = parser.parse_args(argv)
    if args.pretrained_model_path:
        args.model_path = args.pretrained_model_path

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    module = T5QAModule(args)
    collator = T5QACollator(
        tokenizer, max_src_length=args.max_seq_length,
        max_tgt_length=args.max_target_length,
        decoder_start_token_id=module.config.decoder_start_token_id,
        max_knowledge_length=args.max_knowledge_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    if args.do_eval_only:
        import numpy as np
        state = trainer.restore_for_predict(module)
        loader = datamodule.test_dataloader() or \
            datamodule.val_dataloader()
        outputs = trainer.predict(module, loader, state=state)
        with open(args.prediction_res_path, "w", encoding="utf-8") as f:
            for out in outputs:
                for text in tokenizer.batch_decode(
                        np.asarray(out), skip_special_tokens=True):
                    f.write(text + "\n")
        print("predictions saved to", args.prediction_res_path)
    else:
        trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
