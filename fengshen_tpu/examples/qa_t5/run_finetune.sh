#!/bin/bash
# hparams carried from reference: fengshen/examples/qa_t5/run_finetune.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-T5-784M-QA-Chinese}
python -m fengshen_tpu.examples.qa_t5.finetune_t5_cmrc \
    --pretrained_model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --val_file ${VAL_FILE:-dev.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --every_n_train_steps 100 \
    --train_batchsize 8 --val_batchsize 8 \
    --max_seq_length 512 \
    --learning_rate 1e-4 --weight_decay 1e-2 --warmup_ratio 0.1 \
    --min_learning_rate 1e-5 \
    --max_epochs 10 \
    --precision bf16
