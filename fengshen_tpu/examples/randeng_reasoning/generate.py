"""Randeng causal-reasoning demo (deduction + abduction).

Port of the reference driver (reference:
fengshen/examples/randeng_reasoning/ — Randeng-TransformerXL-5B
Abduction/Deduction generation with the fixed prompts).
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

from fengshen_tpu.models.transfo_xl_reasoning import (
    TransfoXLReasoningConfig, TransfoXLReasoningModel, abduction_generate,
    deduction_generate)


def main(argv=None, model=None, params=None, tokenizer=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", type=str, default=None)
    parser.add_argument("--mode", type=str, default="deduction",
                        choices=["deduction", "abduction"])
    parser.add_argument("--input", type=str, default="模型训练数据变多")
    parser.add_argument("--max_out_seq", type=int, default=64)
    args = parser.parse_args(argv)

    if model is None:
        config = TransfoXLReasoningConfig.small_test_config()
        model = TransfoXLReasoningModel(config)
    if params is None:
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
    if tokenizer is None:
        from fengshen_tpu.examples.demo_utils import ToyTokenizer
        tokenizer = ToyTokenizer()

    fn = deduction_generate if args.mode == "deduction" else \
        abduction_generate
    out = fn(model, params, tokenizer, args.input,
             max_out_seq=args.max_out_seq)
    for line in out:
        print(line)
    return out


if __name__ == "__main__":
    main()
