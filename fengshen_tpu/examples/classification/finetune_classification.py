"""Text-classification finetune (AFQMC-style).

Port of reference: fengshen/examples/classification/
finetune_classification.py — the demo workload of the reference's README
("7 GB finetune of Erlangshen-1.3B", demo_classification_afqmc_*.sh).
Thin wrapper over the TextClassificationPipeline train path so the CLI
surface matches the reference scripts.
"""

from __future__ import annotations

import argparse


def main(argv=None):
    from fengshen_tpu.pipelines.text_classification import (
        TextClassificationPipeline)

    parser = argparse.ArgumentParser()
    parser = TextClassificationPipeline.add_pipeline_specific_args(parser)
    parser.add_argument("--num_labels", type=int, default=2)
    args = parser.parse_args(argv)

    pipeline = TextClassificationPipeline(
        args=args, model=getattr(args, "model_path", None),
        num_labels=args.num_labels)
    if args.datasets_name:
        pipeline.train(args.datasets_name)
    else:
        import datasets as hf_datasets
        data_files = {}
        if args.train_file:
            data_files["train"] = args.train_file
        if args.val_file:
            data_files["validation"] = args.val_file
        pipeline.train(hf_datasets.load_dataset(
            args.raw_file_type, data_files=data_files))


if __name__ == "__main__":
    main()
