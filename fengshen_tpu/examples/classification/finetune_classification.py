"""Text-classification finetune — the reference README's demo workload.

Full port of
reference: fengshen/examples/classification/finetune_classification.py:1-389
(the driver behind all 14 `finetune_classification_*.sh` /
`demo_classification_*.sh` shells, including the "7 GB finetune" offload
demo `demo_classification_afqmc_erlangshen_offload.sh:9-33`):

- ``TaskDataset`` / ``TaskCollator`` / ``TaskDataModel`` — jsonl task files
  with configurable field names (``--texta_name/--textb_name/--label_name/
  --id_name``), label schema discovered from the train split (:184-199),
  pair encoding with the RoFormer single-sequence special case (:92-121).
- ``model_dict`` backbone dispatch (:44-51) — here each model_type maps to
  the corresponding flax family; ``huggingface-auto`` resolves through the
  checkpoint's config.json like AutoModelForSequenceClassification.
- ``TaskModel`` — encoder + linear ``cls_layer`` over the pooled/[CLS]
  representation with CE loss (:202-228).
- ``TaskModelCheckpoint`` argparse surface (:299-314) mapped onto the
  orbax UniversalCheckpoint.
- ``save_test`` — predictions written as ``{"id":…, "label": id2label[…]}``
  jsonl (:327-341).

TPU-native differences: the DeepSpeed ZeRO stages of the shells become
mesh flags (``--fsdp_parallel_size`` = ZeRO-3 analog) and
``--offload_optimizer`` (host-resident adam moments — the 7 GB recipe);
training runs as one jitted SPMD step through the shared Trainer.
"""

from __future__ import annotations

import argparse
import importlib
import json
import logging
import os
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.trainer.module import TrainModule

logger = logging.getLogger("fengshen_tpu.classification")

#: model_type → (family module, config class, encoder class)
#: (reference: finetune_classification.py:44-51 `model_dict`; zen1 is
#: commented out there but its shells pass `fengshen-zen1`, so the port
#: supports it for real — without ngram inputs ZEN degrades to BERT)
model_dict: dict[str, tuple[str, str, str]] = {
    "huggingface-bert": (
        "fengshen_tpu.models.bert", "BertConfig", "BertModel"),
    "huggingface-megatron_bert": (
        "fengshen_tpu.models.megatron_bert", "MegatronBertConfig",
        "MegatronBertModel"),
    "fengshen-roformer": (
        "fengshen_tpu.models.roformer", "RoFormerConfig", "RoFormerModel"),
    "fengshen-megatron_t5": (
        "fengshen_tpu.models.t5", "T5Config", "T5EncoderModel"),
    "fengshen-longformer": (
        "fengshen_tpu.models.longformer", "LongformerConfig",
        "LongformerModel"),
    "fengshen-zen1": (
        "fengshen_tpu.models.zen", "ZenConfig", "ZenModel"),
    "fengshen-bart": (
        "fengshen_tpu.models.bart", "BartConfig", "BartModel"),
}

#: config.json model_type → model_dict key, for `huggingface-auto`
#: (the AutoModelForSequenceClassification path of the reference)
_AUTO_TYPES = {
    "bert": "huggingface-bert",
    "roberta": "huggingface-bert",
    "megatron-bert": "huggingface-megatron_bert",
    "roformer": "fengshen-roformer",
    "longformer": "fengshen-longformer",
    "t5": "fengshen-megatron_t5",
    "zen": "fengshen-zen1",
    "bart": "fengshen-bart",
}


def resolve_model_type(model_type: str, pretrained_path: str) -> str:
    """`huggingface-auto` reads the checkpoint's config.json model_type
    (reference dispatches to AutoModelForSequenceClassification:50)."""
    if model_type != "huggingface-auto":
        return model_type
    cfg_file = os.path.join(pretrained_path, "config.json") \
        if os.path.isdir(pretrained_path) else pretrained_path
    try:
        with open(cfg_file) as f:
            raw = json.load(f)
        key = raw.get("fengshen_model_type", raw.get("model_type", "bert"))
    except (OSError, json.JSONDecodeError) as e:
        # hub ids can't be resolved offline; a local dir without a
        # readable config.json is a broken checkpoint — either way the
        # fallback choice must be LOUD, not silent
        logger.warning(
            "huggingface-auto could not read %s (%s); assuming a "
            "MegatronBert-family checkpoint — pass --model_type "
            "explicitly if that is wrong", cfg_file, e)
        key = "megatron-bert"
    if key not in _AUTO_TYPES:
        logger.warning(
            "huggingface-auto: unknown model_type %r in %s; assuming a "
            "MegatronBert-family checkpoint", key, cfg_file)
    return _AUTO_TYPES.get(key, "huggingface-megatron_bert")


def _family(model_type: str):
    mod_name, cfg_name, enc_name = model_dict[model_type]
    mod = importlib.import_module(mod_name)
    return mod, getattr(mod, cfg_name), getattr(mod, enc_name)


# -- data -----------------------------------------------------------------

class TaskDataset:
    """jsonl task split with configurable field names
    (reference: finetune_classification.py:54-84)."""

    def __init__(self, data_path: str, args, label2id: dict):
        self.args = args
        self.label2id = label2id
        self.data = self.load_data(data_path, args)

    def __len__(self) -> int:
        return len(self.data)

    def __getitem__(self, index: int) -> dict:
        return self.data[index]

    def load_data(self, data_path: str, args) -> list[dict]:
        samples = []
        with open(data_path, "r", encoding="utf8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                data = json.loads(line)
                text_id = int(data[args.id_name]) \
                    if args.id_name in data else 0
                texta = data.get(args.texta_name, "")
                textb = data.get(args.textb_name, "")
                label = self.label2id[data[args.label_name]] \
                    if args.label_name in data else 0
                samples.append({args.texta_name: texta,
                                args.textb_name: textb,
                                args.label_name: label, "id": text_id})
        return samples


@dataclass
class TaskCollator:
    """Pair encoding; RoFormer gets texta⟨eos⟩textb as one sequence
    (reference: finetune_classification.py:87-121)."""

    args: Any = None
    tokenizer: Any = None

    def __call__(self, samples: list[dict]) -> dict:
        args, tok = self.args, self.tokenizer
        texta = [s[args.texta_name] for s in samples]
        textb = [s[args.textb_name] for s in samples]
        # pair-vs-single is decided PER SAMPLE, like the reference: one
        # row with an empty textb must not drop textb for the whole
        # batch (ADVICE r4).  padding="max_length" keeps every row the
        # same width, so the two groups reassemble by index.
        pair_idx = [i for i, (a, b) in enumerate(zip(texta, textb))
                    if a != "" and b != ""]
        single_idx = [i for i in range(len(samples)) if i not in pair_idx]

        def encode_pairs(idx):
            a = [texta[i] for i in idx]
            b = [textb[i] for i in idx]
            if args.model_type != "fengshen-roformer":
                return tok(a, b, max_length=args.max_length,
                           padding="max_length",
                           truncation="longest_first",
                           return_tensors="np")
            sep = tok.eos_token or tok.sep_token or ""
            return tok([x + sep + y for x, y in zip(a, b)],
                       max_length=args.max_length, padding="max_length",
                       truncation=True, return_tensors="np")

        def encode_singles(idx):
            return tok([texta[i] for i in idx],
                       max_length=args.max_length, padding="max_length",
                       truncation=True, return_tensors="np")

        parts = []
        if pair_idx:
            parts.append((pair_idx, encode_pairs(pair_idx)))
        if single_idx:
            parts.append((single_idx, encode_singles(single_idx)))
        keys = set().union(*(e.keys() for _, e in parts))
        batch = {}
        for key in ("input_ids", "attention_mask", "token_type_ids"):
            if key not in keys:
                continue
            out = np.zeros((len(samples), args.max_length), np.int32)
            for idx, enc in parts:
                if key in enc:
                    out[idx] = enc[key].astype(np.int32)
            batch[key] = out
        batch["labels"] = np.asarray(
            [int(s[args.label_name]) for s in samples], np.int32)
        batch["id"] = np.asarray([int(s["id"]) for s in samples], np.int32)
        return batch


class _HFView:
    """Row view over an HF dataset split applying the same field
    normalisation as TaskDataset.load_data (labels → schema ids)."""

    def __init__(self, dataset, args, label2id: dict):
        self.dataset = dataset
        self.args = args
        self.label2id = label2id

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, index: int) -> dict:
        args = self.args
        data = self.dataset[int(index)]
        return {
            args.texta_name: data.get(args.texta_name, ""),
            args.textb_name: data.get(args.textb_name, ""),
            args.label_name: self.label2id[data[args.label_name]]
            if args.label_name in data else 0,
            "id": int(data[args.id_name]) if args.id_name in data else 0,
        }


class TaskDataModel:
    """Task datamodule with the reference's flag surface
    (reference: finetune_classification.py:124-199)."""

    @staticmethod
    def add_data_specific_args(parent_args: argparse.ArgumentParser):
        parser = parent_args.add_argument_group("TASK NAME DataModel")
        parser.add_argument("--data_dir", default="./data", type=str)
        parser.add_argument("--num_workers", default=8, type=int)
        parser.add_argument("--train_data", default="train.json", type=str)
        parser.add_argument("--valid_data", default="dev.json", type=str)
        parser.add_argument("--test_data", default="test.json", type=str)
        parser.add_argument("--train_batchsize", default=16, type=int)
        parser.add_argument("--valid_batchsize", default=32, type=int)
        parser.add_argument("--max_length", default=128, type=int)

        parser.add_argument("--texta_name", default="text", type=str)
        parser.add_argument("--textb_name", default="sentence2", type=str)
        parser.add_argument("--label_name", default="label", type=str)
        parser.add_argument("--id_name", default="id", type=str)

        parser.add_argument("--dataset_name", default=None, type=str)
        return parent_args

    def __init__(self, args, tokenizer=None):
        self.args = args
        self.trainer = None  # set by Trainer.fit
        if tokenizer is None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(
                args.pretrained_model_path)
        self.tokenizer = tokenizer
        self.collator = TaskCollator(args=args, tokenizer=tokenizer)
        if args.dataset_name is None:
            train_path = os.path.join(args.data_dir, args.train_data)
            self.label2id, self.id2label = self.load_schema(train_path,
                                                            args)
            self.train_data = TaskDataset(train_path, args, self.label2id)
            self.valid_data = TaskDataset(
                os.path.join(args.data_dir, args.valid_data), args,
                self.label2id)
            self.test_data = TaskDataset(
                os.path.join(args.data_dir, args.test_data), args,
                self.label2id)
        else:
            import datasets as hf_datasets
            ds = hf_datasets.load_dataset(args.dataset_name)
            self.label2id, self.id2label = self._schema_from_rows(
                ds["train"], args)
            # map raw labels → ids exactly like TaskDataset.load_data
            # does for jsonl, so the collator always sees label IDS and
            # save_test's id2label round-trips
            self.train_data = _HFView(ds["train"], args, self.label2id)
            self.valid_data = _HFView(ds["validation"], args,
                                      self.label2id)
            self.test_data = _HFView(ds["test"], args, self.label2id)

    def _loader(self, dataset, batch_size: int, shuffle: bool):
        from fengshen_tpu.data.universal_datamodule import (
            DataLoader, _SimpleBatchSampler)
        from fengshen_tpu.parallel.mesh import (data_parallel_rank,
                                                data_parallel_world_size,
                                                get_mesh)
        mesh = get_mesh()
        rank, world = (0, 1) if mesh is None else (
            data_parallel_rank(mesh), data_parallel_world_size(mesh))
        sampler = _SimpleBatchSampler(
            len(dataset), batch_size, rank, world, shuffle,
            seed=getattr(self.args, "seed", 42),
            drop_last=shuffle)
        return DataLoader(dataset, sampler, self.collator,
                          global_batch_size=batch_size * world)

    def train_dataloader(self):
        return self._loader(self.train_data, self.args.train_batchsize,
                            shuffle=True)

    def val_dataloader(self):
        return self._loader(self.valid_data, self.args.valid_batchsize,
                            shuffle=False)

    def predict_dataloader(self):
        return self._loader(self.test_data, self.args.valid_batchsize,
                            shuffle=False)

    def load_schema(self, data_path: str, args):
        with open(data_path, "r", encoding="utf8") as f:
            rows = [json.loads(line) for line in f if line.strip()]
        return self._schema_from_rows(rows, args)

    @staticmethod
    def _schema_from_rows(rows, args):
        """First-seen label order, as the reference builds it (:184-199)."""
        label_list: list = []
        for data in rows:
            label = data[args.label_name] if args.label_name in data else 0
            if label not in label_list:
                label_list.append(label)
        label2id = {k: i for i, k in enumerate(label_list)}
        id2label = {i: k for i, k in enumerate(label_list)}
        return label2id, id2label


# -- model ----------------------------------------------------------------

class TaskModel(nn.Module):
    """Backbone encoder + linear classifier over the pooled / [CLS]
    representation (reference: finetune_classification.py:202-228
    `taskModel`: ``bert_encoder`` + ``cls_layer``)."""

    config: Any
    model_type: str
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        _, _, encoder_cls = _family(self.model_type)
        if self.model_type == "fengshen-megatron_t5":
            # T5 encoder has no pooler: first-token representation
            # (reference:215-218)
            hidden = encoder_cls(self.config, name="bert_encoder")(
                input_ids, attention_mask=attention_mask,
                deterministic=deterministic)
            encode = hidden[:, 0, :]
        elif self.model_type == "fengshen-bart":
            # encoder-only pass; sentence representation = last real
            # token (the eos position, as HF BartForSequenceClassification
            # pools it)
            hidden = encoder_cls(self.config, name="bert_encoder").encode(
                input_ids, attention_mask=attention_mask,
                deterministic=deterministic)
            if attention_mask is None:
                last = jnp.full((input_ids.shape[0],),
                                input_ids.shape[1] - 1)
            else:
                last = jnp.maximum(attention_mask.sum(-1) - 1, 0)
            encode = jnp.take_along_axis(
                hidden, last[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
        elif self.model_type == "fengshen-zen1":
            _, encode = encoder_cls(self.config, name="bert_encoder")(
                input_ids, attention_mask=attention_mask,
                token_type_ids=token_type_ids,
                deterministic=deterministic)
        else:
            _, encode = encoder_cls(
                self.config, add_pooling_layer=True, name="bert_encoder")(
                input_ids, attention_mask=attention_mask,
                token_type_ids=token_type_ids,
                deterministic=deterministic)
        return nn.Dense(
            self.num_labels,
            kernel_init=nn.initializers.normal(
                getattr(self.config, "initializer_range", 0.02)),
            name="cls_layer")(encode)


class ClassificationModule(TrainModule):
    """The LightningModule analog (reference:231-296 `LitModel`)."""

    def __init__(self, args, config: Optional[Any] = None):
        super().__init__(args)
        self.model_type = resolve_model_type(
            args.model_type, args.pretrained_model_path)
        _, config_cls, _ = _family(self.model_type)
        if config is None:
            config = config_cls.from_pretrained(args.pretrained_model_path)
        self.config = config
        self.model = TaskModel(config, self.model_type,
                               num_labels=args.num_labels)

    @staticmethod
    def add_model_specific_args(parent_args: argparse.ArgumentParser):
        parser = parent_args.add_argument_group("BaseModel")
        parser.add_argument("--num_labels", default=2, type=int)
        parser.add_argument(
            "--offload_params", action="store_true", default=False,
            help="ZeRO-3 analog: params + adam moments live in host "
                 "memory and stream to HBM one layer at a time inside "
                 "the step (reference: megatron_deepspeed.py:55-104 "
                 "offload_param; the 7GB AFQMC recipe). MegatronBert "
                 "backbone only; composes the optimizer offload "
                 "automatically.")
        from fengshen_tpu.trainer.modules import add_lora_args
        add_lora_args(
            parser,
            targets_default=(
                r"(self/(query|key|value)|attention_output_dense)"),
            # the task head is random init — it must train fully
            train_default=r"cls_layer")
        parser.add_argument(
            "--offload_moments_dtype", default="param", type=str,
            choices=["param", "auto", "float32", "bfloat16"],
            help="host-resident adam moment storage dtype under "
                 "--offload_params. 'param' (default) keeps each "
                 "param's own dtype with update math in that dtype — "
                 "bit-parity with the monolithic optax step; 'auto' "
                 "lets the offload policy pick bfloat16 when fp32 "
                 "moments would exceed half of host RAM "
                 "(docs/offload.md); "
                 "'bfloat16' stores moments reduced (halving the host "
                 "memory term that bounds streamable model size) while "
                 "the update math runs in fp32. fp16 is deliberately "
                 "NOT offered: v=g^2 ~ 1e-8 underflows fp16's 5.96e-8 "
                 "subnormal floor and diverges the run; bf16 shares "
                 "fp32's exponent range.")
        return parent_args

    def init_params(self, rng):
        seq = min(int(getattr(self.args, "max_length", 128)), 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        params = self.model.init(rng, ids)["params"]
        imported = self._import_backbone(params.get("bert_encoder"))
        if imported is not None:
            params = dict(params)
            params["bert_encoder"] = imported
        return params

    def _import_backbone(self, init_encoder) -> Optional[Any]:
        """Best-effort torch-weight import through the family converter
        (the reference's `.from_pretrained(...)` at :207-208). Random
        init (returning None) when the path has no importable weights or
        the tree shapes disagree with the config."""
        import jax
        path = getattr(self.args, "pretrained_model_path", None)
        if not path or not os.path.isdir(path):
            return None
        mod, _, _ = _family(self.model_type)
        try:
            convert = importlib.import_module(mod.__name__ + ".convert")
            from fengshen_tpu.utils.convert_common import \
                load_torch_checkpoint
            state = load_torch_checkpoint(path)
            imported = convert.torch_to_params(state, self.config)
        except (ModuleNotFoundError, FileNotFoundError, AttributeError,
                KeyError) as e:
            logger.info("no backbone import from %s (%s); random init",
                        path, e)
            return None
        # converters for the *ForX classes nest the encoder under its
        # module name (often alongside head entries): pick the first
        # candidate subtree whose structure matches the encoder we built
        candidates = [imported]
        if isinstance(imported, dict):
            for key in ("bert_encoder", "bert", "encoder",
                        "megatron_bert", "roformer", "longformer",
                        "zen", "model"):
                if key in imported:
                    candidates.insert(0, imported[key])
        if init_encoder is None:
            return candidates[0]
        want = jax.tree_util.tree_structure(init_encoder)
        for cand in candidates:
            if jax.tree_util.tree_structure(cand) == want:
                return cand
        logger.warning(
            "imported tree from %s does not match the %s encoder "
            "structure; keeping random init", path, self.model_type)
        return None

    def _apply(self, params, batch, deterministic, rng=None):
        kwargs = {"attention_mask": batch.get("attention_mask"),
                  "token_type_ids": batch.get("token_type_ids")}
        if self.model_type == "fengshen-megatron_t5":
            kwargs.pop("token_type_ids")
        rngs = {"dropout": rng} if rng is not None else None
        return self.model.apply({"params": params}, batch["input_ids"],
                                deterministic=deterministic, rngs=rngs,
                                **kwargs)

    def training_loss(self, params, batch, rng):
        logits = self._apply(params, batch, deterministic=False, rng=rng)
        loss, _ = stable_cross_entropy(logits[:, None, :],
                                       batch["labels"][:, None])
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        return loss, {"train_acc": acc}

    def validation_loss(self, params, batch, rng):
        logits = self._apply(params, batch, deterministic=True)
        loss, _ = stable_cross_entropy(logits[:, None, :],
                                       batch["labels"][:, None])
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        return loss, {"val_acc": acc}

    def predict_step(self, params, batch):
        """(ids, logits) — the reference's predict_step (:288-292)."""
        logits = self._apply(params, batch, deterministic=True)
        return {"id": batch["id"], "logits": logits}

    def partition_rules(self):
        _, _, encoder_cls = _family(self.model_type)
        encoder = encoder_cls(self.config)
        if hasattr(encoder, "partition_rules"):
            # config-aware (e.g. MegatronBert picks SCAN_PARTITION_RULES
            # when config.scan_layers)
            rules = list(encoder.partition_rules())
        else:
            mod, _, _ = _family(self.model_type)
            rules = list(getattr(mod, "PARTITION_RULES", []))
        # the family tables end with a ('.*', replicate) catch-all that
        # also covers cls_layer; guarantee one for families that don't
        if not any(pat == ".*" for pat, _ in rules):
            rules.append((".*", P(None)))
        return rules


# -- checkpoint arg surface ------------------------------------------------

def _bool(value: str) -> bool:
    return str(value).lower() in ("true", "1", "yes")


class TaskModelCheckpoint:
    """The reference's checkpoint flag surface (:299-324), realised as an
    orbax UniversalCheckpoint (``--dirpath`` ↦ save/load_ckpt_path)."""

    @staticmethod
    def add_argparse_args(parent_args: argparse.ArgumentParser):
        parser = parent_args.add_argument_group("TaskModelCheckpoint")
        parser.add_argument("--monitor", default="train_loss", type=str)
        parser.add_argument("--mode", default="min", type=str)
        parser.add_argument("--dirpath", default="./log/", type=str)
        parser.add_argument(
            "--filename", default="model-{epoch:02d}-{train_loss:.4f}",
            type=str)
        parser.add_argument("--save_top_k", default=3, type=float)
        parser.add_argument("--every_n_train_steps", default=100,
                            type=float)
        parser.add_argument("--save_weights_only", default=True,
                            type=_bool)
        return parent_args

    def __init__(self, args):
        from fengshen_tpu.utils import UniversalCheckpoint
        args.save_ckpt_path = args.dirpath
        args.load_ckpt_path = args.dirpath
        args.save_top_k = int(args.save_top_k)
        args.every_n_train_steps = int(args.every_n_train_steps or 0)
        args.save_last = False
        args.every_n_epochs = None
        args.save_on_train_epoch_end = None
        self.callbacks = UniversalCheckpoint(args)


# -- predict output --------------------------------------------------------

def save_test(data: list, args, data_model: TaskDataModel,
              rank: int = 0) -> None:
    """Write `{"id":…, "label": id2label[argmax]}` jsonl
    (reference: finetune_classification.py:327-341)."""
    file_name = args.output_save_path + f".{rank}"
    # the tail batch may carry cycled duplicate rows (the sampler pads so
    # DP ranks stay in step) — write each sample id once
    written: set = set()
    with open(file_name, "w", encoding="utf-8") as f:
        for out in data:
            ids = np.asarray(out["id"]).reshape(-1)
            logits = np.asarray(out["logits"])
            for sample_id, sample in zip(ids, logits):
                if int(sample_id) in written:
                    continue
                written.add(int(sample_id))
                label_id = int(np.argmax(sample))
                f.write(json.dumps(
                    {"id": int(sample_id),
                     "label": data_model.id2label[label_id]},
                    ensure_ascii=False) + "\n")
    print("save the result to " + file_name)


# -- param-streaming fit (ZeRO-3 analog) -----------------------------------

def _fit_streamed(args, module: "ClassificationModule", data_model,
                  ckpt=None):
    """Train with host-resident parameter streaming: HBM holds one
    transformer layer's (params, grads, moments) plus boundary
    activations (reference 7GB recipe:
    demo_classification_afqmc_erlangshen_offload.sh:9-33). Returns a
    TrainState the predict path consumes."""
    from fengshen_tpu.trainer.param_streaming import (
        megatron_classifier_stream_spec, run_streamed_fit)

    if module.model_type != "huggingface-megatron_bert":
        raise ValueError(
            "--offload_params streams the MegatronBert backbone (the "
            f"erlangshen recipe); got model_type={module.model_type}")
    params = module.init_params(jax.random.PRNGKey(
        getattr(args, "seed", 42)))
    if ckpt is not None:
        # resume support: restore weights (the streamed checkpoints are
        # weights-only; moments restart) before the engine takes over
        import optax

        from fengshen_tpu.trainer.train_state import TrainState
        state0 = TrainState.create(apply_fn=module.model.apply,
                                   params=params,
                                   tx=optax.set_to_zero())
        class _View:  # maybe_restore records the restored step here
            global_step = 0
            consumed_samples = 0
        state0 = ckpt.maybe_restore(state0, _View(), weights_only=True)
        params = state0.params
    spec = megatron_classifier_stream_spec(module.config, params,
                                           args.num_labels,
                                           deterministic=False)
    del params  # the engine holds the host master copies now

    def log(step, loss, metrics, peak):
        logger.info(
            "streamed step=%d loss=%.4f acc=%.3f grad_norm=%.3g "
            "peak_hbm_gb=%.2f", step, loss,
            metrics.get("acc", float("nan")),
            metrics.get("grad_norm", float("nan")), peak / 1e9)

    return run_streamed_fit(args, spec, data_model.train_dataloader(),
                            module.model.apply, ckpt=ckpt, log=log,
                            park_on_device=True)


# -- main ------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import add_trainer_args

    total_parser = argparse.ArgumentParser("TASK NAME")
    total_parser.add_argument("--pretrained_model_path", default="",
                              type=str)
    total_parser.add_argument("--output_save_path",
                              default="./predict.json", type=str)
    total_parser.add_argument("--model_type", default="huggingface-bert",
                              type=str)
    total_parser.add_argument(
        "--warmup", default=None, type=float,
        help="legacy alias of --warmup_ratio (the bert-3.9B shells)")
    total_parser.add_argument(
        "--do_predict_only", action="store_true", default=False)
    total_parser = TaskDataModel.add_data_specific_args(total_parser)
    total_parser = add_trainer_args(total_parser)
    total_parser = TaskModelCheckpoint.add_argparse_args(total_parser)
    total_parser = add_module_args(total_parser)
    total_parser = ClassificationModule.add_model_specific_args(
        total_parser)
    return total_parser


def main(argv=None):
    from fengshen_tpu.parallel.mesh import data_parallel_rank, get_mesh
    from fengshen_tpu.trainer import Trainer

    args = build_parser().parse_args(argv)
    if args.warmup is not None:
        args.warmup_ratio = args.warmup
    # resolve huggingface-auto ONCE so the collator's RoFormer special
    # case and the module agree on the family
    args.model_type = resolve_model_type(args.model_type,
                                         args.pretrained_model_path)

    data_model = TaskDataModel(args)
    module = ClassificationModule(args)
    from fengshen_tpu.trainer.modules import maybe_wrap_lora
    module = maybe_wrap_lora(module, args)
    trainer = Trainer(args)
    ckpt = TaskModelCheckpoint(args)
    trainer.callbacks.append(ckpt.callbacks)

    if args.do_predict_only:
        state = trainer.restore_for_predict(module)
    elif getattr(args, "offload_params", False):
        state = _fit_streamed(args, module, data_model,
                              ckpt=ckpt.callbacks)
    else:
        state = trainer.fit(module, data_model)
    result = trainer.predict(module, data_model.predict_dataloader(),
                             state=state)
    mesh = get_mesh()
    rank = data_parallel_rank(mesh) if mesh is not None else 0
    save_test(result, args, data_model, rank=rank)


if __name__ == "__main__":
    main()
