#!/bin/bash
# hparams carried from reference: fengshen/examples/classification/finetune_classification_bert-3.9B_wsc.sh
# TPU-native translation: DeepSpeed ZeRO stages -> mesh flags
# (--fsdp_parallel_size = ZeRO-3 analog), fp16 -> bf16,
# Lightning val_check_interval 1.0 (once per epoch) -> 0 (per-epoch).
set -euo pipefail

MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-MegatronBert-3.9B}
DATA_DIR=${DATA_DIR:-./data/wsc_public}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR

python -m fengshen_tpu.examples.classification.finetune_classification \
    --pretrained_model_path $MODEL_PATH \
    --model_type huggingface-megatron_bert \
    --output_save_path $ROOT_DIR/predict.json \
    --data_dir $DATA_DIR \
    --train_data train.json --valid_data dev.json --test_data test.json \
    --train_batchsize 16 --valid_batchsize 56 \
    --max_length 128 \
    --texta_name texta \
    --label_name label --id_name id \
    --learning_rate 0.00001 --weight_decay 0.01 --warmup 0.001 \
    --num_labels 2 \
    --monitor val_acc --mode max --save_top_k 3 \
    --every_n_train_steps 0 --save_weights_only True \
    --dirpath $ROOT_DIR/ckpt \
    --filename model-{epoch:02d}-{val_acc:.4f} \
    --max_epochs 7 --gradient_clip_val 1.0 \
    --val_check_interval 10 \
    --precision bf16 \
    --default_root_dir $ROOT_DIR \
    --fsdp_parallel_size 4
