#!/bin/bash
python -m fengshen_tpu.examples.unimc.example --model_path ${MODEL_PATH:-IDEA-CCNL/Erlangshen-UniMC-RoBERTa-110M-Chinese} --max_steps ${MAX_STEPS:-1000}
