"""UniMC zero/few-shot multiple-choice demo: one-call train + predict.

Port of the reference driver (reference: fengshen/examples/unimc/
example.py:5-86): label options become [MASK]-prefixed choices and the
model picks the option whose mask scores highest; train on a handful of
labelled rows, then predict.
"""

from __future__ import annotations

import argparse

from fengshen_tpu.pipelines.multiplechoice import Pipeline


TRAIN_DATA = [
    {"texta": "凌云研发的国产两轮电动车怎么样，有什么惊喜？", "textb": "",
     "question": "下面新闻属于哪一个类别？",
     "choices": ["教育", "科技", "军事", "旅游"], "label": 1, "id": 0},
    {"texta": "街头偶遇2018款长安CS35，颜值美炸！", "textb": "",
     "question": "下面新闻属于哪一个类别？",
     "choices": ["教育", "科技", "军事", "汽车"], "label": 3, "id": 1},
]

TEST_DATA = [{
    "texta": "街头偶遇2018款长安CS35，颜值美炸！", "textb": "",
    "question": "下面新闻属于哪一个类别？",
    "choices": ["房产", "汽车", "教育", "军事"], "id": 1}]


def main(argv=None, pipeline=None):
    parser = argparse.ArgumentParser("TASK NAME")
    parser = Pipeline.add_pipeline_specific_args(parser)
    args = parser.parse_args(argv)
    if pipeline is None:
        pipeline = Pipeline(args,
                            model=getattr(args, "model_path", None))
    pipeline.train(TRAIN_DATA)
    result = pipeline.predict(TEST_DATA)
    for line in result:
        print(line)
    return result


if __name__ == "__main__":
    main()
