"""ZEN1 sequence-level finetune (TNEWS-style classification).

Port of the reference workload
(reference: fengshen/examples/zen1_finetune/
fengshen_sequence_level_ft_task.py + fs_zen1_tnews.sh): texts are char
tokenized, dictionary n-grams matched into (ngram_ids, ngram_positions)
side inputs, and ZenForSequenceClassification is trained with CE.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.zen import (ZenConfig, ZenForSequenceClassification,
                                     ZenNgramDict)
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class ZenSequenceCollator:
    """{sentence, label} → batch with ngram side inputs
    (reference: convert_examples_to_features in
    fengshen_sequence_level_ft_task.py)."""

    tokenizer: Any
    ngram_dict: ZenNgramDict
    max_seq_length: int = 128
    label2id: Optional[dict] = None
    freq_weighted: bool = False  # True for zen2

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        pad_id = tok.pad_token_id or 0
        max_len = self.max_seq_length
        M = self.ngram_dict.max_ngram_in_seq
        batch = {"input_ids": [], "attention_mask": [], "ngram_ids": [],
                 "ngram_positions": [], "labels": []}
        for sample in samples:
            text = sample.get("sentence") or sample.get("text", "")
            chars = tok.tokenize(text)[: max_len - 2]
            ids = [tok.cls_token_id] + tok.convert_tokens_to_ids(chars) + \
                [tok.sep_token_id]
            ngram_ids, positions, freqs = self.ngram_dict.match(
                chars, with_freqs=True)
            # shift positions by 1 for [CLS], pad to max_len rows
            pos = np.zeros((max_len, M), np.float32)
            pos[1: 1 + len(chars)] = positions
            if self.freq_weighted:
                # zen2 data prep: weight each span by its dictionary
                # frequency, then row-normalise (reference:
                # examples/zen2_finetune/fengshen_sequence_level_ft_task
                # .py:393-404); zen1 feeds the raw 0/1 matrix (reference:
                # examples/zen1_finetune/...:284-286, fusion = plain sum)
                pos = pos * freqs[None, :]
                cover = np.maximum(pos.sum(axis=1, keepdims=True), 1e-10)
                pos = pos / cover
            pad = max_len - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["ngram_ids"].append(ngram_ids)
            batch["ngram_positions"].append(pos)
            label = sample.get("label", 0)
            if self.label2id is not None:
                label = self.label2id.get(str(label), 0)
            batch["labels"].append(int(label))
        return {k: np.asarray(v) for k, v in batch.items()}


class ZenSequenceModule(TrainModule):
    def __init__(self, args, config: Optional[ZenConfig] = None,
                 num_labels: int = 2):
        super().__init__(args)
        import dataclasses
        if config is None and getattr(args, "model_path", None):
            config = ZenConfig.from_pretrained(args.model_path)
        config = dataclasses.replace(config, num_labels=num_labels)
        self.config = config
        self.model = ZenForSequenceClassification(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("zen1 finetune")
        parser.add_argument("--max_seq_length", type=int, default=128)
        parser.add_argument("--num_labels", type=int, default=15)
        parser.add_argument("--ngram_dict_path", type=str, default=None)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        # include ngram side inputs so the ngram encoder params are created
        ngram_ids = jnp.zeros((1, 8), jnp.int32)
        ngram_pos = jnp.zeros((1, seq, 8), jnp.int32)
        return self.model.init(rng, ids, ngram_ids=ngram_ids,
                               ngram_positions=ngram_pos)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            ngram_ids=batch["ngram_ids"],
            ngram_positions=batch["ngram_positions"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, _ = stable_cross_entropy(logits[:, None, :],
                                       batch["labels"][:, None])
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        return loss, {"acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = ZenSequenceModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    ngram_dict = ZenNgramDict(args.ngram_dict_path or args.model_path)
    collator = ZenSequenceCollator(tokenizer, ngram_dict,
                                   max_seq_length=args.max_seq_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = ZenSequenceModule(args, num_labels=args.num_labels)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
