#!/bin/bash
# Launcher for zen1_finetune.fengshen_sequence_level_ft_task (reference pattern: fengshen/examples/zen1_finetune/fs_zen1_tnews.sh)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-ZEN1-224M-Chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.zen1_finetune.fengshen_sequence_level_ft_task \
    --model_path $MODEL_PATH \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --train_file $TRAIN_FILE --num_labels 15 --max_seq_length 128
