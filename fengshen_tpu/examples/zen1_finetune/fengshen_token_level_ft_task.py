"""ZEN1 token-level (NER) finetune.

Port of the reference workload
(reference: fengshen/examples/zen1_finetune/fengshen_token_level_ft_task.py
+ ner_zen1_ontonotes4.sh): char-level BIO tagging with n-gram side inputs
on ZenForTokenClassification.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.data.sequence_tagging_dataloader import ConllDataset
from fengshen_tpu.examples.sequence_tagging.finetune_sequence_tagging \
    import build_label_maps
from fengshen_tpu.models.zen import (ZenConfig, ZenForTokenClassification,
                                     ZenNgramDict)
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class ZenTaggingCollator:
    """char BIO labels + matched n-grams → padded batch
    (reference: convert_examples_to_features of the token-level task)."""

    tokenizer: Any
    ngram_dict: ZenNgramDict
    label2id: dict
    max_seq_length: int = 128
    freq_weighted: bool = False  # True for zen2

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        pad_id = tok.pad_token_id or 0
        max_len = self.max_seq_length
        M = self.ngram_dict.max_ngram_in_seq
        batch = {"input_ids": [], "attention_mask": [], "ngram_ids": [],
                 "ngram_positions": [], "labels": []}
        for sample in samples:
            chars = list(sample["text"])[: max_len - 2]
            tags = sample["labels"][: max_len - 2]
            ids = [tok.cls_token_id] + [
                tok.convert_tokens_to_ids(c) for c in chars] + \
                [tok.sep_token_id]
            labels = [-100] + [self.label2id.get(t, 0) for t in tags] + \
                [-100]
            ngram_ids, positions, freqs = self.ngram_dict.match(
                chars, with_freqs=True)
            pos = np.zeros((max_len, M), np.float32)
            pos[1: 1 + len(chars)] = positions
            if self.freq_weighted:
                # zen2 data prep: weight each span by its dictionary
                # frequency, then row-normalise (reference:
                # examples/zen2_finetune/fengshen_sequence_level_ft_task
                # .py:393-404); zen1 feeds the raw 0/1 matrix (reference:
                # examples/zen1_finetune/...:284-286, fusion = plain sum)
                pos = pos * freqs[None, :]
                cover = np.maximum(pos.sum(axis=1, keepdims=True), 1e-10)
                pos = pos / cover
            pad = max_len - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["ngram_ids"].append(ngram_ids)
            batch["ngram_positions"].append(pos)
            batch["labels"].append(labels + [-100] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


class ZenTaggingModule(TrainModule):
    def __init__(self, args, config: Optional[ZenConfig] = None,
                 num_labels: int = 9):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = ZenConfig.from_pretrained(args.model_path)
        self.config = config
        self.model = ZenForTokenClassification(config,
                                               num_labels=num_labels)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("zen1 ner")
        parser.add_argument("--max_seq_length", type=int, default=128)
        parser.add_argument("--ngram_dict_path", type=str, default=None)
        parser.add_argument("--data_dir", type=str, default=None)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        ngram_ids = jnp.zeros((1, 8), jnp.int32)
        ngram_pos = jnp.zeros((1, seq, 8), jnp.int32)
        return self.model.init(rng, ids, ngram_ids=ngram_ids,
                               ngram_positions=ngram_pos)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            ngram_ids=batch["ngram_ids"],
            ngram_positions=batch["ngram_positions"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, _ = stable_cross_entropy(logits, batch["labels"])
        valid = batch["labels"] != -100
        acc = ((logits.argmax(-1) == batch["labels"]) * valid).sum() / \
            jnp.maximum(valid.sum(), 1)
        return loss, {"token_acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    import os

    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = ZenTaggingModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    if not args.data_dir:
        parser.error("--data_dir with train.char.bio is required")
    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    ngram_dict = ZenNgramDict(args.ngram_dict_path or args.model_path)
    datasets = {}
    for split, fname in (("train", "train.char.bio"),
                         ("validation", "dev.char.bio")):
        path = os.path.join(args.data_dir, fname)
        if os.path.exists(path):
            datasets[split] = ConllDataset(path)
    if "train" not in datasets:
        parser.error(f"no train.char.bio under {args.data_dir}")
    label2id, _ = build_label_maps(list(datasets.values()))
    collator = ZenTaggingCollator(tokenizer, ngram_dict, label2id,
                                  max_seq_length=args.max_seq_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args,
                                     datasets=datasets)
    module = ZenTaggingModule(args, num_labels=len(label2id))
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
