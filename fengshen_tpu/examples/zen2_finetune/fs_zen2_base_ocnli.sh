#!/bin/bash
# ZEN2-base ocnli classification finetune
# hparams carried from reference: fengshen/examples/zen2_finetune/fs_zen2_base_ocnli.sh
# TPU: single host by default; scale via the mesh flags
# (--tensor_model_parallel_size / --fsdp_parallel_size) and
# launchers/slurm_multihost.sh or launchers/gke_tpu_job.yaml.
set -euo pipefail

MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-ZEN2-345M-Chinese}
DATA_DIR=${DATA_DIR:-./data/ocnli}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR

python -m fengshen_tpu.examples.zen2_finetune.fengshen_sequence_level_ft_task \
    --model_path $MODEL_PATH \
    --train_file $DATA_DIR/train.json \
    --val_file $DATA_DIR/dev.json \
    --test_file $DATA_DIR/test1.1.json \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --monitor val_acc --mode max --save_top_k 3 \
    --train_batchsize 32 \
    --val_batchsize 16 \
    --max_seq_length 128 \
    --num_labels 3 \
    --learning_rate 2e-5 \
    --weight_decay 0.01 \
    --warmup_ratio 0.01 \
    --max_epochs 7 \
    --precision bf16 \
    --seed 1234
