"""ZEN2 token-level (NER) finetune.

Port of the reference workload (reference:
fengshen/examples/zen2_finetune/fengshen_token_level_ft_task.py + the 12
ner_zen2_* shell configs): the zen1 CoNLL pipeline and collator on the
relative-attention ZEN2 encoder with freq-weighted ngram fusion.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import jax.numpy as jnp

from fengshen_tpu.examples.zen1_finetune.fengshen_token_level_ft_task \
    import ConllDataset, ZenTaggingCollator, build_label_maps
from fengshen_tpu.models.zen import ZenNgramDict
from fengshen_tpu.models.zen2 import Zen2Config, Zen2ForTokenClassification
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


class Zen2TaggingModule(TrainModule):
    def __init__(self, args, config: Optional[Zen2Config] = None,
                 num_labels: int = 9):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = Zen2Config.from_pretrained(args.model_path)
        self.config = config
        self.model = Zen2ForTokenClassification(config,
                                                num_labels=num_labels)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("zen2 ner")
        parser.add_argument("--max_seq_length", type=int, default=128)
        parser.add_argument("--ngram_dict_path", type=str, default=None)
        parser.add_argument("--data_dir", type=str, default=None)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        ngram_ids = jnp.zeros((1, 8), jnp.int32)
        ngram_pos = jnp.zeros((1, seq, 8), jnp.int32)
        return self.model.init(rng, ids, ngram_ids=ngram_ids,
                               ngram_positions=ngram_pos)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            ngram_ids=batch["ngram_ids"],
            ngram_positions=batch["ngram_positions"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, _ = stable_cross_entropy(logits, batch["labels"])
        valid = batch["labels"] != -100
        acc = ((logits.argmax(-1) == batch["labels"]) * valid).sum() / \
            jnp.maximum(valid.sum(), 1)
        return loss, {"token_acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = Zen2TaggingModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    if not args.data_dir:
        parser.error("--data_dir with train.char.bio is required")
    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    ngram_dict = ZenNgramDict(args.ngram_dict_path or args.model_path)
    datasets = {}
    for split, fname in (("train", "train.char.bio"),
                         ("validation", "dev.char.bio")):
        path = os.path.join(args.data_dir, fname)
        if os.path.exists(path):
            datasets[split] = ConllDataset(path)
    if "train" not in datasets:
        parser.error(f"no train.char.bio under {args.data_dir}")
    label2id, _ = build_label_maps(list(datasets.values()))
    # zen2 weights ngram spans by dictionary frequency in data prep
    collator = ZenTaggingCollator(tokenizer, ngram_dict, label2id,
                                  max_seq_length=args.max_seq_length,
                                  freq_weighted=True)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args,
                                     datasets=datasets)
    module = Zen2TaggingModule(args, num_labels=len(label2id))
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
