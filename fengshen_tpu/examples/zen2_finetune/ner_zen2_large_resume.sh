#!/bin/bash
# ZEN2-large resume NER finetune
# hparams carried from reference: fengshen/examples/zen2_finetune/ner_zen2_large_resume.sh
# TPU: single host by default; scale via the mesh flags
# (--tensor_model_parallel_size / --fsdp_parallel_size) and
# launchers/slurm_multihost.sh or launchers/gke_tpu_job.yaml.
set -euo pipefail

MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-ZEN2-668M-Chinese}
DATA_DIR=${DATA_DIR:-./data/resume}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR

python -m fengshen_tpu.examples.zen2_finetune.fengshen_token_level_ft_task \
    --model_path $MODEL_PATH \
    --data_dir $DATA_DIR \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --monitor val_f1 --mode max --save_top_k 3 \
    --train_batchsize 32 \
    --val_batchsize 16 \
    --max_seq_length 256 \
    --learning_rate 3e-5 \
    --weight_decay 0.01 \
    --warmup_ratio 0.01 \
    --max_epochs 5 \
    --precision bf16 \
    --seed 1234
