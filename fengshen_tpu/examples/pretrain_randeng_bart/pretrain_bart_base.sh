#!/bin/bash
# hparams carried from reference: fengshen/examples/pretrain_randeng_bart/pretrain_bart_base.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-BART-139M}
python -m fengshen_tpu.examples.pretrain_randeng_bart.pretrain_bart \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize 32 \
    --learning_rate 1e-4 --weight_decay 1e-1 --warmup_ratio 0.01 \
    --max_epochs 10 --log_every_n_steps 1 \
    --precision bf16
