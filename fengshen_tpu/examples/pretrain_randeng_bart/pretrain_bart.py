"""Randeng-BART denoising pretraining over an indexed corpus.

Port of the reference workload
(reference: fengshen/examples/pretrain_randeng_bart/pretrain_bart.py):
fairseq-style text infilling via data.megatron_dataloader.BartDataset
(sentence permutation + Poisson whole-word infilling) feeding
BartForConditionalGeneration with shifted-decoder CE.
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.data.megatron_dataloader import (BartDataset,
                                                   MMapIndexedDataset)
from fengshen_tpu.models.bart import BartConfig, BartForConditionalGeneration
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


class BartPretrainModule(TrainModule):
    def __init__(self, args, config: Optional[BartConfig] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = BartConfig.from_pretrained(args.model_path)
        self.config = config
        self.model = BartForConditionalGeneration(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("bart pretrain")
        parser.add_argument("--data_prefix", type=str, default=None,
                            help="MMapIndexedDataset path prefix")
        parser.add_argument("--max_seq_length", type=int, default=512)
        parser.add_argument("--masked_lm_prob", type=float, default=0.15)
        return parent_parser

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids, ids)["params"]

    def training_loss(self, params, batch, rng):
        # decoder input = clean target shifted right with decoder_start
        labels = batch["labels"]
        start = self.config.decoder_start_token_id
        safe = jnp.where(labels == -100, self.config.pad_token_id
                         if hasattr(self.config, "pad_token_id") else 0,
                         labels)
        dec_in = jnp.concatenate(
            [jnp.full((labels.shape[0], 1), start, labels.dtype),
             safe[:, :-1]], axis=1)
        logits = self.model.apply(
            {"params": params}, batch["input_ids"], dec_in,
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits, labels)
        return loss, {"n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = BartPretrainModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    dataset = BartDataset(
        MMapIndexedDataset(args.data_prefix), tokenizer,
        max_seq_length=args.max_seq_length,
        masked_lm_prob=args.masked_lm_prob)
    datamodule = UniversalDataModule(tokenizer=tokenizer, args=args,
                                     datasets={"train": dataset})
    module = BartPretrainModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
