"""Shared helpers for the tokenizer-less demo paths of the example
scripts (toy char→id encoding used when no checkpoint/tokenizer is
given)."""

from __future__ import annotations

import numpy as np


def toy_encode(text: str, max_len: int = 8) -> list[int]:
    """Deterministic char→id toy encoding (ids 3..95, 0 = pad)."""
    ids = [min(3 + (ord(c) % 90), 95) for c in text[:max_len]]
    return ids + [0] * (max_len - len(ids))


def toy_encode_batch(texts: list[str], max_len: int = 16) -> np.ndarray:
    return np.asarray([toy_encode(t, max_len) for t in texts], np.int32)


class ToyTokenizer:
    """encode/decode stub with BERT-ish special ids for demo mains."""

    pad_token_id, eos_token_id = 0, 2

    def encode(self, text: str) -> list[int]:
        return [min(3 + (ord(c) % 90), 95) for c in text] + [2]

    def decode(self, ids) -> str:
        return " ".join(str(int(i)) for i in ids if int(i) > 2)
