#!/bin/bash
# Erlangshen-MegatronBert pretrain launcher — TPU counterpart of the
# reference's pretrain_erlangshen_base.sh (reference: fengshen/examples/
# pretrain_erlangshen_bert/pretrain_erlangshen_base.sh:25-41 heredoc
# ZeRO-1 JSON → PL_DEEPSPEED_CONFIG_PATH). ZeRO ≈ --fsdp_parallel_size.

MODEL_PATH=${MODEL_PATH:-"./erlangshen-bert-base"}
TRAIN_FILE=${TRAIN_FILE:-"./corpus.jsonl"}
OUTPUT=${OUTPUT:-"./runs/erlangshen_base"}

python -m fengshen_tpu.examples.pretrain_erlangshen_bert.pretrain_erlangshen \
    --model_path "$MODEL_PATH" \
    --train_file "$TRAIN_FILE" \
    --max_seq_length 512 \
    --masked_lm_prob 0.15 \
    --train_batchsize 32 \
    --fsdp_parallel_size 8 \
    --learning_rate 1e-4 \
    --warmup_ratio 0.01 \
    --scheduler_type polynomial \
    --max_steps 100000 \
    --every_n_train_steps 1000 \
    --save_ckpt_path "$OUTPUT/ckpt" \
    --load_ckpt_path "$OUTPUT/ckpt" \
    --default_root_dir "$OUTPUT"
