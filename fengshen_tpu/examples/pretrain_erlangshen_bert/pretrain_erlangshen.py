"""Erlangshen-MegatronBert pretraining: MLM (whole-word, jieba) + SOP.

Port of the reference workload
(reference: fengshen/examples/pretrain_erlangshen_bert/
pretrain_erlangshen.py:35-237): the ErLangShenCollator pipeline
(ChineseSentenceSplitter → SOP pairing → truncation → [CLS]/[SEP] assembly →
whole-word MLM → padding with -100 labels) and a pretrain module whose loss
is MLM CE + sentence-order CE. Run:

    python -m fengshen_tpu.examples.pretrain_erlangshen_bert.pretrain_erlangshen \
        --train_file corpus.json --model_path <bert-dir> --max_steps 1000 ...
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.data.data_utils import (ChineseSentenceSplitter,
                                          create_masked_lm_predictions,
                                          create_tokens_and_tokentypes,
                                          get_a_and_b_segments,
                                          truncate_segments)
from fengshen_tpu.models.megatron_bert import (MegatronBertConfig,
                                               MegatronBertForPreTraining)
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class ErLangShenCollator:
    """text → MLM+SOP sample (reference: pretrain_erlangshen.py:35-123)."""

    tokenizer: Any
    max_seq_length: int = 512
    masked_lm_prob: float = 0.15
    content_key: str = "text"
    seed: int = 42
    zh_tokenizer: Optional[Any] = None

    def __post_init__(self):
        self.splitter = ChineseSentenceSplitter()
        self.np_rng = np.random.RandomState(self.seed)
        if self.zh_tokenizer is None:
            try:
                import jieba
                self.zh_tokenizer = jieba.lcut
            except ImportError:
                self.zh_tokenizer = None
        vocab = self.tokenizer.get_vocab()
        self.vocab_id_list = list(vocab.values())
        self.vocab_id_to_token = {v: k for k, v in vocab.items()}
        self.cls_id = self.tokenizer.cls_token_id
        self.sep_id = self.tokenizer.sep_token_id
        self.mask_id = self.tokenizer.mask_token_id
        self.pad_id = self.tokenizer.pad_token_id or 0

    def _encode_sentences(self, text: str) -> list[list[int]]:
        sentences = self.splitter.tokenize(text)
        return [self.tokenizer.encode(s, add_special_tokens=False)
                for s in sentences if s]

    def __call__(self, samples: list[dict]) -> dict:
        batch = {"input_ids": [], "attention_mask": [], "token_type_ids": [],
                 "labels": [], "next_sentence_label": []}
        max_len = self.max_seq_length
        for sample in samples:
            sents = self._encode_sentences(sample[self.content_key])
            sents = [s for s in sents if s]
            if len(sents) < 2:  # single sentence: split in half for SOP
                flat = sents[0] if sents else [self.mask_id]
                half = max(len(flat) // 2, 1)
                sents = [flat[:half], flat[half:] or [flat[-1]]]
            a, b, is_random = get_a_and_b_segments(sents, self.np_rng)
            truncate_segments(a, b, len(a), len(b), max_len - 3, self.np_rng)
            tokens, tokentypes = create_tokens_and_tokentypes(
                a, b, self.cls_id, self.sep_id)
            masked_tokens, positions, labels = create_masked_lm_predictions(
                tokens, self.vocab_id_list, self.vocab_id_to_token,
                self.masked_lm_prob, self.cls_id, self.sep_id, self.mask_id,
                max_predictions_per_seq=int(
                    self.masked_lm_prob * max_len) + 1,
                np_rng=self.np_rng, zh_tokenizer=self.zh_tokenizer)
            mlm_labels = [-100] * len(tokens)
            for pos, label in zip(positions, labels):
                mlm_labels[pos] = label

            pad = max_len - len(masked_tokens)
            batch["input_ids"].append(masked_tokens + [self.pad_id] * pad)
            batch["attention_mask"].append([1] * len(masked_tokens) +
                                           [0] * pad)
            batch["token_type_ids"].append(tokentypes + [0] * pad)
            batch["labels"].append(mlm_labels + [-100] * pad)
            batch["next_sentence_label"].append(int(is_random))
        return {k: np.asarray(v) for k, v in batch.items()}


class ErLangShenBert(TrainModule):
    """Reference: pretrain_erlangshen.py:126-197."""

    def __init__(self, args, config: Optional[MegatronBertConfig] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = MegatronBertConfig.from_pretrained(args.model_path)
        self.config = config
        self.model = MegatronBertForPreTraining(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("Erlangshen Bert")
        parser.add_argument("--masked_lm_prob", type=float, default=0.15)
        parser.add_argument("--max_seq_length", type=int, default=512)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        mlm_logits, sop_logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"],
            deterministic=False, rngs={"dropout": rng})
        mlm_loss, n_tokens = stable_cross_entropy(mlm_logits,
                                                  batch["labels"])
        sop_loss, _ = stable_cross_entropy(
            sop_logits[:, None, :], batch["next_sentence_label"][:, None])
        # mlm accuracy over masked positions (reference logs mlm_acc,
        # reference: pretrain_erlangshen.py:147-160)
        valid = batch["labels"] != -100
        acc = ((mlm_logits.argmax(-1) == batch["labels"]) * valid).sum() \
            / jnp.maximum(valid.sum(), 1)
        return mlm_loss + sop_loss, {"mlm_loss": mlm_loss,
                                     "sop_loss": sop_loss,
                                     "mlm_acc": acc,
                                     "n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()

    def flops_per_token(self):
        cfg = self.config
        per_layer = 4 * cfg.hidden_size ** 2 + \
            2 * cfg.hidden_size * cfg.intermediate_size
        return 6.0 * (cfg.num_hidden_layers * per_layer +
                      cfg.hidden_size * cfg.vocab_size)


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = ErLangShenBert.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = ErLangShenCollator(tokenizer,
                                  max_seq_length=args.max_seq_length,
                                  masked_lm_prob=args.masked_lm_prob)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = ErLangShenBert(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
