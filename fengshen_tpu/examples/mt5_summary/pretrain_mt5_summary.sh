#!/bin/bash
# hparams carried from reference: fengshen/examples/mt5_summary/pretrain_mt5_summary.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-google/mt5-large}
python -m fengshen_tpu.examples.mt5_summary.mt5_summary \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --monitor train_loss --mode min \
    --train_batchsize 16 --val_batchsize 16 \
    --learning_rate 1e-4 --weight_decay 0.1 --warmup_ratio 0.01 \
    --max_epochs 2 \
    --precision bf16
