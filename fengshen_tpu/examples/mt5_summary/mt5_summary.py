"""Randeng-mT5 summarization finetune (LCSTS).

Port of the reference workload
(reference: fengshen/examples/mt5_summary/mt5_summary.py:1-233): mT5
finetune over {text, summary} pairs. Reuses the shared Seq2SeqCollator /
Seq2SeqModule from examples.summary (the reference's mt5_summary duplicates
the summary module with an mT5 model class; here model_type='t5' covers
mT5 checkpoints via the converter). The reference's FastAPI serving demo
(fastapi_mt5_summary.py) maps to the framework-level REST API
(fengshen_tpu.api.main) with a text-generation pipeline config.
"""

from __future__ import annotations

import argparse


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.examples.summary.seq2seq_summary import (
        Seq2SeqCollator, Seq2SeqModule, build_model)
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    group = parser.add_argument_group("mt5 summary")
    group.add_argument("--max_src_length", default=512, type=int)
    group.add_argument("--max_tgt_length", default=128, type=int)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    model, config = build_model("t5", args.model_path)
    collator = Seq2SeqCollator(
        tokenizer, max_src_length=args.max_src_length,
        max_tgt_length=args.max_tgt_length,
        decoder_start_token_id=getattr(config, "decoder_start_token_id", 0))
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = Seq2SeqModule(args, model, config)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
