#!/bin/bash
# Launcher for mt5_summary.mt5_summary (reference pattern: fengshen/examples/mt5_summary/pretrain_mt5_summary.sh)
# Multi-host TPU: run this script on every host with JAX_COORDINATOR_ADDRESS
# set (see docs/multihost.md); single host needs no extra flags.
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-MT5-220M}
ROOT_DIR=${ROOT_DIR:-./workdir/mt5_summary.mt5_summary}

python -m fengshen_tpu.examples.mt5_summary.mt5_summary \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-32} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --max_src_length 512 --max_tgt_length 128
