"""GAVAE data-augmentation demo: train the latent GAN on a handful of
labelled latents, then sample class-conditional text
(reference: fengshen/examples/GAVAE/generate.py)."""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.davae import DAVAEModel
from fengshen_tpu.models.gavae import GAVAEConfig, GAVAEModel


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=4)
    parser.add_argument("--label", type=int, default=0)
    parser.add_argument("--gan_steps", type=int, default=20)
    parser.add_argument("--max_length", type=int, default=12)
    args = parser.parse_args(argv)

    cfg = GAVAEConfig.small_test_config()
    vae = DAVAEModel(cfg.vae)
    vae_params = vae.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    gavae = GAVAEModel(cfg, vae_model=vae, vae_params=vae_params)

    rng = np.random.RandomState(0)
    latents = jnp.asarray(np.concatenate(
        [rng.randn(8, cfg.latent_size) + 2.0,
         rng.randn(8, cfg.latent_size) - 2.0]), jnp.float32)
    labels = jnp.asarray([0] * 8 + [1] * 8, jnp.int32)
    d_loss, g_loss = gavae.train_gan(latents, labels, steps=args.gan_steps)
    print(f"gan trained: d_loss={d_loss:.3f} g_loss={g_loss:.3f}")
    out = gavae.generate(args.n, label=args.label,
                         max_length=args.max_length)
    for row in np.asarray(out):
        print(" ".join(str(int(t)) for t in row))
    return np.asarray(out)


if __name__ == "__main__":
    main()
