"""YuyuanQA-style interactive demo.

Port of reference: fengshen/examples/FastDemo/YuyuanQA.py — a minimal
question-answering demo over a finetuned causal LM ("Question:...Answer:"
format), reading questions from stdin and generating answers.
"""

from __future__ import annotations

import argparse
import sys


def answer(model, params, tokenizer, question: str,
           max_new_tokens: int = 64) -> str:
    import jax.numpy as jnp

    from fengshen_tpu.utils.generate import generate

    prompt = f"Question:{question} Answer:"
    ids = tokenizer.encode(prompt, add_special_tokens=False)
    out = generate(model, params, jnp.asarray([ids], jnp.int32),
                   max_new_tokens=max_new_tokens,
                   eos_token_id=tokenizer.eos_token_id)
    new_tokens = list(out[0][len(ids):])
    return tokenizer.decode(new_tokens, skip_special_tokens=True).strip()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from fengshen_tpu.models.gpt2.convert import load_hf_pretrained

    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", required=True, type=str)
    parser.add_argument("--max_new_tokens", default=64, type=int)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    config, params = load_hf_pretrained(args.model_path)
    model = GPT2LMHeadModel(config)

    print("YuyuanQA demo — type a question, empty line to exit")
    for line in sys.stdin:
        q = line.strip()
        if not q:
            break
        print(answer(model, params, tokenizer, q,
                     max_new_tokens=args.max_new_tokens), flush=True)


if __name__ == "__main__":
    main()
