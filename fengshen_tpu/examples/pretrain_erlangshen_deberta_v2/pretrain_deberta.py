"""Erlangshen-DeBERTa-v2 whole-word-masking MLM pretraining.

Port of the reference workload
(reference: fengshen/examples/pretrain_erlangshen_deberta_v2/
pretrain_deberta.py:34-227): a DeBERTaV2Collator that tokenizes raw text,
applies jieba whole-word masking via `create_masked_lm_predictions`
(masking_style='bert'), and trains DebertaV2ForMaskedLM on the MLM CE. Run:

    python -m fengshen_tpu.examples.pretrain_erlangshen_deberta_v2.pretrain_deberta \
        --train_file corpus.json --model_path <deberta-dir> --max_steps 10000 ...
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.data.data_utils import create_masked_lm_predictions
from fengshen_tpu.models.deberta_v2 import (DebertaV2Config,
                                            DebertaV2ForMaskedLM)
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class DeBERTaV2Collator:
    """text → whole-word-masked MLM sample
    (reference: pretrain_deberta.py:34-110)."""

    tokenizer: Any
    max_seq_length: int = 512
    masked_lm_prob: float = 0.15
    content_key: str = "text"
    seed: int = 42

    def __post_init__(self):
        self.np_rng = np.random.RandomState(self.seed)
        try:
            import jieba
            self.zh_tokenizer = jieba.lcut
        except ImportError:  # pragma: no cover
            self.zh_tokenizer = None
        vocab = self.tokenizer.get_vocab()
        self.vocab_id_list = list(vocab.values())
        self.vocab_id_to_token = {v: k for k, v in vocab.items()}

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        max_len = self.max_seq_length
        batch = {"input_ids": [], "attention_mask": [], "labels": []}
        for sample in samples:
            body = tok.encode(sample[self.content_key],
                              add_special_tokens=False)[: max_len - 2]
            tokens = [tok.cls_token_id] + body + [tok.sep_token_id]
            masked_tokens, positions, labels = create_masked_lm_predictions(
                tokens, self.vocab_id_list, self.vocab_id_to_token,
                self.masked_lm_prob, tok.cls_token_id, tok.sep_token_id,
                tok.mask_token_id,
                max_predictions_per_seq=int(
                    self.masked_lm_prob * max_len) + 1,
                np_rng=self.np_rng, masking_style="bert",
                zh_tokenizer=self.zh_tokenizer)
            mlm_labels = [-100] * len(tokens)
            for pos, label in zip(positions, labels):
                mlm_labels[pos] = label
            pad_id = tok.pad_token_id or 0
            pad = max_len - len(masked_tokens)
            batch["input_ids"].append(masked_tokens + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(masked_tokens) +
                                           [0] * pad)
            batch["labels"].append(mlm_labels + [-100] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


class DebertaPretrainModule(TrainModule):
    """MLM loss (reference: pretrain_deberta.py:115-180)."""

    def __init__(self, args, config: Optional[DebertaV2Config] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = DebertaV2Config.from_pretrained(args.model_path)
        self.config = config
        self.model = DebertaV2ForMaskedLM(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("DeBERTa pretrain")
        parser.add_argument("--masked_lm_prob", type=float, default=0.15)
        parser.add_argument("--max_seq_length", type=int, default=512)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits,
                                                      batch["labels"])
        valid = batch["labels"] != -100
        acc = ((logits.argmax(-1) == batch["labels"]) * valid).sum() / \
            jnp.maximum(valid.sum(), 1)
        return loss, {"mlm_acc": acc, "n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = DebertaPretrainModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = DeBERTaV2Collator(tokenizer,
                                 max_seq_length=args.max_seq_length,
                                 masked_lm_prob=args.masked_lm_prob)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = DebertaPretrainModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
