"""Evaluate a Taiyi-SD checkpoint: generate → CLIP-score.

Port of reference: fengshen/examples/finetune_taiyi_stable_diffusion/
evaluate_model.py — the reference generates images for a prompt list and
scores them with Chinese-CLIP similarity (plus open_clip aesthetics and a
timm watermark head, both of which require external checkpoints that
cannot be fetched here; CLIP score is the model-quality signal and is
ported). TPU-native: our sampling loop + Taiyi CLIP towers, one jitted
scoring pass.
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np

DEMO_PROMPTS = ["飞流直下三千尺，油画", "一只可爱的猫", "城市夜景，赛博朋克"]


def clip_score(clip_model, clip_params, input_ids, attention_mask,
               images, image_size: int = 224) -> np.ndarray:
    """Cosine similarity between image and text embeddings (the CLIP
    score of reference evaluate_model.py). TaiyiCLIPModel returns
    already-normalised embeddings."""
    imgs = jax.image.resize(
        jnp.asarray(images),
        (len(images), image_size, image_size, images[0].shape[-1]),
        method="bilinear")
    text_emb, image_emb, _ = clip_model.apply(
        {"params": clip_params}, input_ids, imgs,
        attention_mask=attention_mask)
    return np.asarray(jnp.sum(image_emb * text_emb, axis=-1))


def main(argv=None):
    parser = argparse.ArgumentParser("taiyi-sd evaluate")
    parser.add_argument("--model_path", type=str, default=None)
    parser.add_argument("--clip_path", type=str, default=None,
                        help="Taiyi CLIP checkpoint for scoring")
    parser.add_argument("--prompt_file", type=str, default=None,
                        help="jsonl with {'prompt': ...} rows")
    parser.add_argument("--image_size", type=int, default=512)
    parser.add_argument("--num_steps", type=int, default=50)
    parser.add_argument("--guidance_scale", type=float, default=7.5)
    parser.add_argument("--out", type=str, default="eval_scores.json")
    args = parser.parse_args(argv)

    if args.prompt_file:
        with open(args.prompt_file, encoding="utf-8") as f:
            prompts = [json.loads(line)["prompt"] for line in f
                       if line.strip()]
    else:
        prompts = DEMO_PROMPTS

    # generation path reuses the chinese demo's model/params bootstrap
    import tempfile

    from fengshen_tpu.examples.stable_diffusion_chinese.demo import (
        main as demo_main)
    images = []
    with tempfile.TemporaryDirectory() as tmp:
        for i, prompt in enumerate(prompts):
            arr = demo_main(["--model_path", args.model_path or "",
                             "--prompt", prompt,
                             "--image_size", str(args.image_size),
                             "--num_steps", str(args.num_steps),
                             "--guidance_scale", str(args.guidance_scale),
                             "--out", f"{tmp}/gen_{i}.png"])
            images.append(np.asarray(arr)[0])

    # scoring towers (text config from the CLIP checkpoint when given;
    # demo-scale otherwise)
    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.clip import CLIPVisionConfig, TaiyiCLIPModel
    if args.clip_path:
        from transformers import AutoTokenizer
        text_config = BertConfig.from_pretrained(args.clip_path)
        vision_config = CLIPVisionConfig()
        tokenizer = AutoTokenizer.from_pretrained(args.clip_path)
        enc = tokenizer(prompts, padding="max_length", truncation=True,
                        max_length=77, return_tensors="np")
        input_ids = enc["input_ids"].astype(np.int32)
        attention_mask = enc["attention_mask"].astype(np.int32)
    else:
        text_config = BertConfig.small_test_config()
        vision_config = CLIPVisionConfig.small_test_config()
        from fengshen_tpu.examples.demo_utils import toy_encode_batch
        input_ids = toy_encode_batch(prompts)
        attention_mask = np.ones_like(input_ids)
    clip_model = TaiyiCLIPModel(text_config, vision_config)
    size = vision_config.image_size
    clip_params = None
    if args.clip_path:
        # scoring with RANDOM clip weights would make every score noise:
        # import the checkpoint or refuse
        try:
            from fengshen_tpu.models.clip.convert import torch_to_params
            from fengshen_tpu.utils.convert_common import (
                load_torch_checkpoint)
            state = dict(load_torch_checkpoint(args.clip_path))
            text_state = {k: v for k, v in state.items()
                          if not k.startswith(("vision", "visual"))}
            clip_params = torch_to_params(
                text_state, state, text_config, vision_config,
                text_projection=state.get("text_projection.weight"),
                visual_projection=state.get("visual_projection.weight"),
                logit_scale=state.get("logit_scale"))
        except (FileNotFoundError, KeyError) as e:
            raise SystemExit(
                f"--clip_path {args.clip_path} has no importable "
                f"weights ({e}); refusing to report CLIP scores from "
                f"random towers") from e
    if clip_params is None:
        # demo mode (no checkpoint): scores exercise the pipeline only
        print("note: no --clip_path — scoring with demo-scale random "
              "towers; scores are NOT a model-quality signal")
        clip_params = clip_model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
            jnp.zeros((1, size, size, 3)))["params"]

    scores = clip_score(clip_model, clip_params, input_ids,
                        attention_mask, np.stack(images),
                        image_size=size)
    report = {"prompts": prompts,
              "clip_scores": [float(s) for s in scores],
              "mean_clip_score": float(scores.mean())}
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, ensure_ascii=False, indent=1)
    print(json.dumps(report, ensure_ascii=False))
    return report


if __name__ == "__main__":
    main()
