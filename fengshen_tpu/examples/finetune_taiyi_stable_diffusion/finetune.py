"""Taiyi Stable Diffusion finetune (Chinese latent diffusion).

Port of the reference workload
(reference: fengshen/examples/finetune_taiyi_stable_diffusion/
finetune.py:67-158): caption+image pairs → VAE latents (×0.18215) → noise +
timesteps → UNet ε-prediction MSE, with frozen text/VAE towers
(`--train_whole_model` to unfreeze, reference :91-100).
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax
import jax.numpy as jnp

from fengshen_tpu.data.clip_dataloader import ImageTextCSVDataset, SDCollator
from fengshen_tpu.models.bert import BertConfig
from fengshen_tpu.models.stable_diffusion import (DDPMScheduler,
                                                  TaiyiStableDiffusion,
                                                  diffusion_loss)
from fengshen_tpu.models.stable_diffusion.autoencoder_kl import VAEConfig
from fengshen_tpu.models.stable_diffusion.unet import UNetConfig
from fengshen_tpu.trainer.module import TrainModule


class TaiyiSDModule(TrainModule):
    """reference: finetune.py StableDiffusion module."""

    def __init__(self, args, text_config: Optional[BertConfig] = None,
                 vae_config: Optional[VAEConfig] = None,
                 unet_config: Optional[UNetConfig] = None):
        super().__init__(args)
        if text_config is None and getattr(args, "model_path", None):
            text_config = BertConfig.from_pretrained(args.model_path)
        self._pipeline_params = None
        if vae_config is None and unet_config is None and (
                getattr(args, "sd_pipeline_path", None) or
                getattr(args, "faithful_towers", False)):
            # released diffusers dir → faithful SD-1.x towers + direct
            # weight import (reference: finetune.py:81-89
            # StableDiffusionPipeline.from_pretrained); --faithful_towers
            # → same architecture, random init
            from fengshen_tpu.models.stable_diffusion.convert import (
                resolve_towers)
            unet_config, vae_config, self._pipeline_params = \
                resolve_towers(
                    getattr(args, "sd_pipeline_path", None),
                    faithful=getattr(args, "faithful_towers", False))
        self.model = TaiyiStableDiffusion(
            text_config, vae_config or VAEConfig(),
            unet_config or UNetConfig())
        self.config = text_config
        self.scheduler = DDPMScheduler(
            prediction_type=getattr(args, "prediction_type", "epsilon"))

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("taiyi sd")
        parser.add_argument("--image_size", type=int, default=512)
        parser.add_argument("--max_length", type=int, default=77)
        parser.add_argument("--prediction_type", type=str,
                            default="epsilon",
                            choices=["epsilon", "v_prediction"])
        parser.add_argument("--train_whole_model", action="store_true",
                            default=False,
                            help="unfreeze text encoder + VAE "
                                 "(reference: finetune.py:91-100)")
        parser.add_argument("--train_csv", type=str, default=None)
        parser.add_argument("--image_root", type=str, default=None)
        parser.add_argument("--sd_pipeline_path", type=str, default=None,
                            help="released diffusers pipeline dir: use "
                                 "the faithful SD-1.x towers and import "
                                 "its unet/vae weights directly")
        parser.add_argument("--faithful_towers", action="store_true",
                            default=False,
                            help="full SD-1.x tower architecture "
                                 "(random init) without a pipeline dir")
        return parent_parser

    def init_params(self, rng):
        size = getattr(self.args, "image_size", 64)
        ids = jnp.zeros((1, 8), jnp.int32)
        pixels = jnp.zeros((1, size, size, 3), jnp.float32)
        t = jnp.zeros((1,), jnp.int32)
        latent_shape = self.model.vae_config.latent_shape(size)
        noise = jnp.zeros((1,) + latent_shape, jnp.float32)
        params = self.model.init(rng, ids, pixels, t, noise)["params"]
        if self._pipeline_params is not None:
            params = dict(params)
            params.update(self._pipeline_params)
            # drop the host copy (~3.8 GB at real SD scale) — init_params
            # runs once and the trainer owns the live tree from here
            self._pipeline_params = None
        return params

    def _denoise_pred(self, params, batch, rng):
        """Shared preamble: freeze towers, sample noise/timesteps, run the
        pipeline. Returns (pred, latents, noise, timesteps). Subclasses
        (dreambooth) override only the loss reduction."""
        if not getattr(self.args, "train_whole_model", False):
            # UNet-only training: freeze text tower + VAE
            params = dict(params)
            for key in list(params):
                if key in ("text_encoder", "vae"):
                    params[key] = jax.lax.stop_gradient(params[key])
        rng_t, rng_n, rng_vae, rng_drop = jax.random.split(rng, 4)
        pixels = batch["pixel_values"]
        latent_shape = self.model.vae_config.latent_shape(pixels.shape[1])
        timesteps = jax.random.randint(
            rng_t, (pixels.shape[0],), 0, self.scheduler.num_train_timesteps)
        noise = jax.random.normal(rng_n,
                                  (pixels.shape[0],) + latent_shape)
        pred, latents = self.model.apply(
            {"params": params}, batch["input_ids"], pixels, timesteps,
            noise, attention_mask=batch.get("attention_mask"),
            rng=rng_vae, deterministic=False, rngs={"dropout": rng_drop})
        return pred, latents, noise, timesteps

    def training_loss(self, params, batch, rng):
        pred, latents, noise, timesteps = self._denoise_pred(params, batch,
                                                             rng)
        loss = diffusion_loss(
            pred, latents, noise, timesteps, self.scheduler,
            prediction_type=getattr(self.args, "prediction_type", "epsilon"))
        return loss, {}

    def partition_rules(self):
        if hasattr(self.model, "partition_rules"):
            return self.model.partition_rules()
        return super().partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = TaiyiSDModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    datasets = {}
    if args.train_csv:
        datasets["train"] = ImageTextCSVDataset(args.train_csv,
                                                image_root=args.image_root)
    collator = SDCollator(tokenizer, image_size=args.image_size,
                          max_length=args.max_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args,
                                     datasets=datasets or None)
    module = TaiyiSDModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
