#!/bin/bash
# Launcher for finetune_taiyi_stable_diffusion.finetune (reference pattern: fengshen/examples/finetune_taiyi_stable_diffusion/finetune.sh)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Taiyi-Stable-Diffusion-1B-Chinese-v0.1}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.finetune_taiyi_stable_diffusion.finetune \
    --model_path $MODEL_PATH \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --train_csv $TRAIN_CSV --image_size 512
