#!/bin/bash
# hparams carried from reference: fengshen/examples/finetune_taiyi_stable_diffusion/evaluate.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Taiyi-Stable-Diffusion-1B-Chinese-v0.1}
python -m fengshen_tpu.examples.finetune_taiyi_stable_diffusion.evaluate \
    --model_path $MODEL_PATH \
    --clip_path ${CLIP_PATH:-} \
    --prompt_file ${PROMPT_FILE:-} \
    --image_size 512 --num_steps 50 \
    --out $ROOT_DIR/eval_scores.json
