"""Sequence tagging (NER) finetune: linear / CRF / span heads.

Port of the reference workload
(reference: fengshen/examples/sequence_tagging/
finetune_sequence_tagging.py:44-316): `--model_type` selects among
bert-linear / bert-crf / bert-span heads (reference `_model_dict`), with the
matching collator building BIO (or span start/end) labels from CoNLL data,
and entity-level P/R/F1 via metrics.SeqEntityScore.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.data.sequence_tagging_dataloader import ConllDataset
from fengshen_tpu.models.megatron_bert import MegatronBertConfig
from fengshen_tpu.models.tagging import BertCrf, BertLinear, BertSpan
from fengshen_tpu.trainer.module import TrainModule

_MODEL_DICT = {
    "bert-linear": BertLinear,
    "bert-crf": BertCrf,
    "bert-span": BertSpan,
}


def build_label_maps(datasets: list) -> tuple[dict, dict]:
    """Scan the corpus for the BIO tag set (reference: DataProcessor
    get_labels)."""
    tags = {"O"}
    for ds in datasets:
        for i in range(len(ds)):
            tags.update(ds[i]["labels"])
    id2label = {i: t for i, t in enumerate(sorted(tags))}
    return {t: i for i, t in id2label.items()}, id2label


@dataclass
class TaggingCollator:
    """char-level BIO labels → padded token batch
    (reference: sequence_tagging_collator CollatorForLinear/Crf/Span)."""

    tokenizer: Any
    label2id: dict
    max_seq_length: int = 128
    model_type: str = "bert-linear"

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        pad_id = tok.pad_token_id or 0
        max_len = self.max_seq_length
        batch: dict = {"input_ids": [], "attention_mask": [],
                       "token_type_ids": [], "labels": []}
        for sample in samples:
            chars = list(sample["text"])[: max_len - 2]
            tags = sample["labels"][: max_len - 2]
            ids = [tok.cls_token_id] + [
                tok.convert_tokens_to_ids(c) if hasattr(
                    tok, "convert_tokens_to_ids") else tok.encode(
                        c, add_special_tokens=False)[0]
                for c in chars] + [tok.sep_token_id]
            labels = [-100] + [self.label2id.get(t, 0) for t in tags] + [-100]
            pad = max_len - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["token_type_ids"].append([0] * max_len)
            batch["labels"].append(labels + [-100] * pad)
        out = {k: np.asarray(v) for k, v in batch.items()}
        if self.model_type == "bert-span":
            # start/end pointer labels from BIO (reference: CollatorForSpan).
            # Entity-type ids start at 1: 0 is reserved for "no entity
            # boundary here" and must not collide with a real type.
            lab = out.pop("labels")
            start = np.zeros_like(lab)
            end = np.zeros_like(lab)
            id2label = {v: k for k, v in self.label2id.items()}
            etype2id = self.span_type2id()
            for b in range(lab.shape[0]):
                i = 0
                while i < lab.shape[1]:
                    lid = lab[b, i]
                    tag = id2label.get(int(lid), "O")
                    if tag.startswith("B-"):
                        ent = tag[2:]
                        j = i
                        while (j + 1 < lab.shape[1] and
                               id2label.get(int(lab[b, j + 1]), "O")
                               == "I-" + ent):
                            j += 1
                        etype = etype2id[ent]
                        start[b, i] = etype
                        end[b, j] = etype
                        i = j + 1
                    else:
                        i += 1
            start[lab == -100] = -100
            end[lab == -100] = -100
            out["start_labels"] = start
            out["end_labels"] = end
        return out

    def span_type2id(self) -> dict:
        """entity type → id, 1-based (0 = background)."""
        ents = sorted({t[2:] for t in self.label2id if t.startswith("B-")})
        return {e: i + 1 for i, e in enumerate(ents)}


class TaggingModule(TrainModule):
    """reference: finetune_sequence_tagging.py LitModel."""

    def __init__(self, args, config: Optional[MegatronBertConfig] = None,
                 num_labels: int = 9):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = MegatronBertConfig.from_pretrained(args.model_path)
        self.config = config
        self.model_type = getattr(args, "model_type", "bert-linear")
        self.model = _MODEL_DICT[self.model_type](config,
                                                  num_labels=num_labels)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("sequence tagging")
        parser.add_argument("--model_type", default="bert-linear", type=str,
                            choices=sorted(_MODEL_DICT))
        parser.add_argument("--max_seq_length", type=int, default=128)
        parser.add_argument("--data_dir", default=None, type=str)
        parser.add_argument("--decode_type", default="bio", type=str)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        # init through the loss path so label-dependent params (the CRF
        # transitions) are created
        if self.model_type == "bert-span":
            return self.model.init(rng, ids, start_labels=ids,
                                   end_labels=ids)["params"]
        return self.model.init(rng, ids, labels=ids)["params"]

    def training_loss(self, params, batch, rng):
        if self.model_type == "bert-span":
            loss, _ = self.model.apply(
                {"params": params}, batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                start_labels=batch["start_labels"],
                end_labels=batch["end_labels"],
                deterministic=False, rngs={"dropout": rng})
            return loss, {}
        loss, logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"],
            labels=batch["labels"],
            deterministic=False, rngs={"dropout": rng})
        valid = batch["labels"] != -100
        acc = ((logits.argmax(-1) == batch["labels"]) * valid).sum() / \
            jnp.maximum(valid.sum(), 1)
        return loss, {"token_acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    import os

    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = TaggingModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    datasets = {}
    for split, fname in (("train", "train.char.bio"),
                         ("validation", "dev.char.bio"),
                         ("test", "test.char.bio")):
        path = os.path.join(args.data_dir, fname)
        if os.path.exists(path):
            datasets[split] = ConllDataset(path)
    label2id, id2label = build_label_maps(list(datasets.values()))
    collator = TaggingCollator(tokenizer, label2id,
                               max_seq_length=args.max_seq_length,
                               model_type=args.model_type)
    if args.model_type == "bert-span":
        # span heads classify entity TYPES (+1 background), not BIO tags
        num_labels = len(collator.span_type2id()) + 1
    else:
        num_labels = len(label2id)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args,
                                     datasets=datasets)
    module = TaggingModule(args, num_labels=num_labels)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
