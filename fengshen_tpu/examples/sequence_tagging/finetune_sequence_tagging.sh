#!/bin/bash
# Launcher for sequence_tagging.finetune_sequence_tagging (reference: fengshen/examples/sequence_tagging/finetune_sequence_tagging.sh (bert + linear decode head; DECODE_TYPE=crf/span/biaffine for the other heads))
# Multi-host TPU: run this script on every host with JAX_COORDINATOR_ADDRESS
# set (see docs/multihost.md); single host needs no extra flags.
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-MegatronBert-1.3B}
ROOT_DIR=${ROOT_DIR:-./workdir/sequence_tagging.finetune_sequence_tagging}

python -m fengshen_tpu.examples.sequence_tagging.finetune_sequence_tagging \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-32} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --model_type bert-${DECODE_TYPE:-linear} --data_dir $DATA_DIR
