#!/bin/bash
# hparams carried from reference: fengshen/examples/summary/randeng_t5_70M_summary.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-T5-77M}
python -m fengshen_tpu.examples.summary.seq2seq_summary \
    --model_type t5 \
    --pretrained_model_path $MODEL_PATH \
    --output_save_path $ROOT_DIR/predict.json \
    --datasets_name lcsts \
    --val_datasets_field val \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --monitor val_loss --mode min --save_top_k 3 --save_last \
    --train_batchsize 64 --val_batchsize 64 --test_batchsize 64 \
    --max_enc_length 128 --max_dec_length 64 \
    --prompt "" \
    --learning_rate 1e-4 --weight_decay 1e-2 \
    --max_epochs 2 \
    --precision bf16
