"""Seq2seq summarization finetune (LCSTS-style).

Port of reference: fengshen/examples/summary/seq2seq_summary.py (and the
pegasus/mt5_summary variants) — encoder-decoder finetune over
{text, summary} pairs with teacher forcing; works with T5, BART, or
Pegasus via --model_type.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class Seq2SeqCollator:
    """Generic seq2seq batching (encode → truncate → eos → shifted decoder
    input → fixed-length pad). Task collators (QG, translation, QA) subclass
    and override `source_text` / `target_text` only, so the padding/shift
    contract lives in one place."""

    tokenizer: Any
    max_src_length: int = 512
    max_tgt_length: int = 128
    decoder_start_token_id: int = 0
    text_key: str = "text"
    summary_key: str = "summary"
    #: task prefix prepended to the source (reference: seq2seq_summary.py
    #: :158 `--prompt`, default "summarize:")
    prompt: str = ""

    def source_text(self, sample: dict) -> str:
        return self.prompt + sample[self.text_key]

    def target_text(self, sample: dict) -> str:
        return sample[self.summary_key]

    def __call__(self, samples: list[dict]) -> dict:
        pad = self.tokenizer.pad_token_id or 0
        eos = self.tokenizer.eos_token_id
        batch = {"input_ids": [], "attention_mask": [],
                 "decoder_input_ids": [], "labels": []}
        for s in samples:
            src = self.tokenizer.encode(self.source_text(s),
                                        add_special_tokens=False
                                        )[: self.max_src_length - 1]
            if eos is not None:
                src = src + [eos]
            tgt = self.tokenizer.encode(self.target_text(s),
                                        add_special_tokens=False
                                        )[: self.max_tgt_length - 1]
            if eos is not None:
                tgt = tgt + [eos]
            dec_in = [self.decoder_start_token_id] + tgt[:-1]
            ps = self.max_src_length - len(src)
            pt = self.max_tgt_length - len(tgt)
            batch["input_ids"].append(src + [pad] * ps)
            batch["attention_mask"].append([1] * len(src) + [0] * ps)
            batch["decoder_input_ids"].append(dec_in + [pad] * pt)
            batch["labels"].append(tgt + [-100] * pt)
        return {k: np.asarray(v) for k, v in batch.items()}


class Seq2SeqModule(TrainModule):
    def __init__(self, args, model, config):
        super().__init__(args)
        self.model = model
        self.config = config

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            batch["decoder_input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, n = vocab_parallel_cross_entropy(logits, batch["labels"])
        return loss, {"n_tokens": n}

    def partition_rules(self):
        return self.model.partition_rules()

    jit_predict = True

    def predict_step(self, params, batch):
        """Beam-search summary decode (reference: the mt5_summary /
        qa_t5 predict paths call HF `generate(num_beams=...)`, e.g.
        fengshen/examples/mt5_summary/fastapi_mt5_summary.py:51-55)."""
        from fengshen_tpu.utils.generate import seq2seq_predict_step
        return seq2seq_predict_step(
            self.model, self.config, self.args, params, batch,
            max_new_tokens=self.args.max_tgt_length)


def build_model(model_type: str, model_path=None, config=None):
    if model_type == "t5":
        from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
        config = config or (T5Config.from_pretrained(model_path)
                            if model_path else T5Config.small_test_config())
        return T5ForConditionalGeneration(config), config
    if model_type == "bart":
        from fengshen_tpu.models.bart import (BartConfig,
                                              BartForConditionalGeneration)
        config = config or (BartConfig.from_pretrained(model_path)
                            if model_path else
                            BartConfig.small_test_config())
        return BartForConditionalGeneration(config), config
    if model_type == "pegasus":
        from fengshen_tpu.models.pegasus import (
            PegasusConfig, PegasusForConditionalGeneration)
        config = config or (PegasusConfig.from_pretrained(model_path)
                            if model_path else
                            PegasusConfig.small_test_config())
        return PegasusForConditionalGeneration(config), config
    raise ValueError(f"unknown model_type {model_type!r}")


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    group = parser.add_argument_group("summary")
    group.add_argument("--model_type", default="t5", type=str,
                       choices=["t5", "bart", "pegasus"])
    group.add_argument("--max_src_length", default=512, type=int)
    group.add_argument("--max_tgt_length", default=128, type=int)
    group.add_argument("--num_beams", default=1, type=int)
    group.add_argument("--length_penalty", default=1.0, type=float)
    group.add_argument("--repetition_penalty", default=1.0,
                       type=float)
    group.add_argument("--no_repeat_ngram_size", default=0,
                       type=int)
    group.add_argument("--min_length", default=0, type=int)
    # the reference driver's eval surface (reference: fengshen/examples/
    # summary/seq2seq_summary.py:144-158)
    group.add_argument("--do_eval_only", action="store_true",
                       default=False)
    group.add_argument("--pretrained_model_path", default=None, type=str,
                       help="alias of --model_path (reference flag name)")
    group.add_argument("--output_save_path", default="./predict.json",
                       type=str)
    group.add_argument("--prompt", default="summarize:", type=str)
    group.add_argument("--rouge_keys", default="rougeL,rouge1,rouge2",
                       type=str)
    group.add_argument("--max_enc_length", default=None, type=int,
                       help="alias of --max_src_length (reference name)")
    group.add_argument("--max_dec_length", default=None, type=int,
                       help="alias of --max_tgt_length (reference name)")
    from fengshen_tpu.trainer.modules import add_lora_args
    add_lora_args(
        parser,
        # T5/BART/Pegasus attention projections (both self and cross)
        targets_default=(
            r"(self_attention|cross_attention|self_attn|encoder_attn)"
            r"/(q|k|v|o|q_proj|k_proj|v_proj|out_proj)/kernel"))
    args = parser.parse_args(argv)
    if args.pretrained_model_path:
        args.model_path = args.pretrained_model_path
    if args.max_enc_length:
        args.max_src_length = args.max_enc_length
    if args.max_dec_length:
        args.max_tgt_length = args.max_dec_length

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    model, config = build_model(args.model_type, args.model_path)
    collator = Seq2SeqCollator(
        tokenizer, max_src_length=args.max_src_length,
        max_tgt_length=args.max_tgt_length,
        decoder_start_token_id=getattr(config, "decoder_start_token_id", 0),
        prompt=args.prompt)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = Seq2SeqModule(args, model, config)
    from fengshen_tpu.trainer.modules import maybe_wrap_lora
    module = maybe_wrap_lora(module, args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    if args.do_eval_only:
        state = trainer.restore_for_predict(module)
    else:
        state = trainer.fit(module, datamodule)
    test_loader = datamodule.test_dataloader() \
        if hasattr(datamodule, "test_dataloader") else None
    if test_loader is not None:
        evaluate_and_save(trainer, module, tokenizer, test_loader, args,
                          state)


def evaluate_and_save(trainer, module, tokenizer, loader, args,
                      state) -> dict:
    """Decode the test split, write prediction jsonl, print char-level
    ROUGE (reference: seq2seq_summary.py:82-120
    validation_epoch_end + save_prediction_to_file)."""
    import json

    from fengshen_tpu.metrics.rouge import rouge_scores

    # ONE pass: materialize the batches, predict over that exact list,
    # and take references from the same batches — alignment by
    # construction (a second loader sweep would re-tokenize the split
    # and silently mis-pair under any future sampler change)
    batches = list(loader)
    outputs = trainer.predict(module, batches, state=state)
    preds, refs = [], []
    with open(args.output_save_path, "w", encoding="utf-8") as f:
        for batch, out in zip(batches, outputs):
            tokens = np.asarray(out["tokens"] if isinstance(out, dict)
                                else out)
            texts = tokenizer.batch_decode(tokens,
                                           skip_special_tokens=True)
            preds.extend(texts)
            for t in texts:
                f.write(json.dumps({"pred": t}, ensure_ascii=False) + "\n")
            labels = np.where(batch["labels"] < 0, 0, batch["labels"])
            refs.extend(tokenizer.batch_decode(
                labels, skip_special_tokens=True))
    keys = tuple(k.strip() for k in args.rouge_keys.split(","))
    scores = rouge_scores(preds, refs, keys=keys)
    print("rouge:", json.dumps(scores, ensure_ascii=False))
    return scores


if __name__ == "__main__":
    main()
