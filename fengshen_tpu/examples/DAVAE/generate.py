"""DAVAE latent-space text generation / augmentation demo.

Port of the reference demo (reference: fengshen/examples/DAVAE/generate.py
— `DAVAEModel.simulate_batch` round-trips input sentences through the
latent space to produce paraphrase-like augmentations).
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.davae import (DAVAEConfig, DAVAEModel,
                                       simulate_batch)


def main(argv=None, model=None, params=None, tokenizer=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", type=str, default=None)
    parser.add_argument("--max_length", type=int, default=32)
    parser.add_argument("--std_scale", type=float, default=1.0)
    parser.add_argument("--sentences", nargs="*", default=[
        "今天天气很好", "我们去公园散步"])
    args = parser.parse_args(argv)

    if model is None:
        config = DAVAEConfig.small_test_config()
        model = DAVAEModel(config)
    if params is None:
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]

    if tokenizer is not None:
        enc = [tokenizer.encode(s) for s in args.sentences]
        max_len = max(len(e) for e in enc)
        ids = np.zeros((len(enc), max_len), np.int32)
        for i, e in enumerate(enc):
            ids[i, :len(e)] = e
    else:  # demo path without a tokenizer: toy ids
        from fengshen_tpu.examples.demo_utils import toy_encode_batch
        ids = toy_encode_batch(args.sentences, max_len=16)

    out = simulate_batch(model, params, jnp.asarray(ids),
                         rng=jax.random.PRNGKey(1),
                         max_length=args.max_length)
    for row in np.asarray(out):
        text = tokenizer.decode([int(t) for t in row]) if tokenizer else \
            " ".join(str(int(t)) for t in row)
        print(text)
    return np.asarray(out)


if __name__ == "__main__":
    main()
