#!/bin/bash
python -m fengshen_tpu.examples.ubert.example --model_path ${MODEL_PATH:-IDEA-CCNL/Erlangshen-Ubert-110M-Chinese} --max_steps ${MAX_STEPS:-1000}
