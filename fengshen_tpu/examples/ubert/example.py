"""UBERT unified information-extraction demo: one-call train + predict.

Port of the reference driver (reference: fengshen/examples/ubert/
example.py:7-110): instruction-style samples {task_type, subtask_type,
text, choices:[{entity_type, entity_list:[{entity_name, entity_idx}]}]}
fed straight to UbertPipelines.fit / .predict.
"""

from __future__ import annotations

import argparse

from fengshen_tpu.pipelines.information_extraction import Pipeline


TRAIN_DATA = [{
    "task_type": "抽取任务", "subtask_type": "实体识别",
    "text": "彭小军认为，国内银行现在走的是台湾的发卡模式",
    "choices": [
        {"entity_type": "地址", "label": 0, "entity_list": [
            {"entity_name": "台湾", "entity_type": "地址",
             "entity_idx": [[15, 16]]}]},
        {"entity_type": "人物姓名", "label": 0, "entity_list": [
            {"entity_name": "彭小军", "entity_type": "人物姓名",
             "entity_idx": [[0, 2]]}]},
    ], "id": 0}]

TEST_DATA = [{
    "task_type": "抽取任务", "subtask_type": "实体识别",
    "text": "就天涯网推出彩票服务频道是否是业内人士所谓的打政策擦边球",
    "choices": [{"entity_type": "公司"}, {"entity_type": "人物姓名"}],
    "id": 1}]


def main(argv=None, pipeline=None):
    parser = argparse.ArgumentParser("TASK NAME")
    parser = Pipeline.pipelines_args(parser)
    args = parser.parse_args(argv)
    if pipeline is None:
        pipeline = Pipeline(args,
                            model=getattr(args, "model_path", None))
    pipeline.fit(TRAIN_DATA)
    result = pipeline.predict(TEST_DATA)
    for line in result:
        print(line)
    return result


if __name__ == "__main__":
    main()
