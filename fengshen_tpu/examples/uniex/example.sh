#!/bin/bash
python -m fengshen_tpu.examples.uniex.example --model_path ${MODEL_PATH:-IDEA-CCNL/Erlangshen-UniEX-RoBERTa-110M-Chinese}
