#!/bin/bash
# hparams carried from reference: fengshen/examples/uniex/predict.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-UniEX-RoBERTa-110M-Chinese}
DATA_DIR=${DATA_DIR:-./data/cluener}
python -m fengshen_tpu.examples.uniex.example \
    --model_path $MODEL_PATH \
    --fast_ex_mode \
    --test_file $DATA_DIR/dev.json \
    --output_path $ROOT_DIR/predict.json \
    --max_length 512 \
    --max_entity_types 16
