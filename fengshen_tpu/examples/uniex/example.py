"""UniEX unified extraction demo: typed-span prediction.

Port of the reference driver (reference: fengshen/examples/uniex/
example.py:17-80): entity-type prompts + text in one sequence; the
triaffine-style span scorer returns typed entities per requested type.
"""

from __future__ import annotations

import argparse

from fengshen_tpu.models.uniex import UniEXPipelines


TEST_DATA = [{
    "task_type": "实体识别",
    "text": "彭小军认为，国内银行现在走的是台湾的发卡模式",
    "choices": [{"entity_type": "地址"}, {"entity_type": "人物姓名"}],
    "id": 0}]


def main(argv=None, pipeline=None):
    parser = argparse.ArgumentParser("TASK NAME")
    parser = UniEXPipelines.pipelines_args(parser)
    args = parser.parse_args(argv)
    if pipeline is None:
        pipeline = UniEXPipelines(args,
                                  model=getattr(args, "model_path", None))
    result = pipeline.predict(TEST_DATA)
    for line in result:
        print(line)
    return result


if __name__ == "__main__":
    main()
