"""UniEX unified extraction demo: typed-span prediction.

Port of the reference driver (reference: fengshen/examples/uniex/
example.py:17-80): entity-type prompts + text in one sequence; the
triaffine-style span scorer returns typed entities per requested type.
"""

from __future__ import annotations

import argparse

from fengshen_tpu.models.uniex import UniEXPipelines


TEST_DATA = [{
    "task_type": "实体识别",
    "text": "彭小军认为，国内银行现在走的是台湾的发卡模式",
    "choices": [{"entity_type": "地址"}, {"entity_type": "人物姓名"}],
    "id": 0}]


def _load_jsonl(path: str) -> list:
    import json
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def main(argv=None, pipeline=None):
    parser = argparse.ArgumentParser("TASK NAME")
    parser = UniEXPipelines.pipelines_args(parser)
    # reference: uniex train.sh / predict.sh surface — --train switches
    # to finetune mode; --fast_ex_mode is the reference's fast-extraction
    # decode (one joint pass instead of per-type rescoring; our decoder
    # is already single-pass, so the flag is accepted for recipe parity)
    parser.add_argument("--train", action="store_true", default=False)
    parser.add_argument("--fast_ex_mode", action="store_true",
                        default=False)
    parser.add_argument("--output_path", default=None, type=str)
    args = parser.parse_args(argv)
    if pipeline is None:
        pipeline = UniEXPipelines(args,
                                  model=getattr(args, "model_path", None))
    if args.train and getattr(args, "train_file", None):
        dev = _load_jsonl(args.val_file) if getattr(args, "val_file",
                                                    None) else None
        pipeline.fit(_load_jsonl(args.train_file), dev)
    data = _load_jsonl(args.test_file) \
        if getattr(args, "test_file", None) else TEST_DATA
    result = pipeline.predict(data)
    if args.output_path:
        import json
        with open(args.output_path, "w", encoding="utf-8") as f:
            for line in result:
                f.write(json.dumps(line, ensure_ascii=False) + "\n")
    else:
        for line in result:
            print(line)
    return result


if __name__ == "__main__":
    main()
