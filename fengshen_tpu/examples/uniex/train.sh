#!/bin/bash
# hparams carried from reference: fengshen/examples/uniex/train.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-UniEX-RoBERTa-110M-Chinese}
DATA_DIR=${DATA_DIR:-./data/cluener}
python -m fengshen_tpu.examples.uniex.example \
    --model_path $MODEL_PATH \
    --train \
    --train_file $DATA_DIR/train.json \
    --val_file $DATA_DIR/dev.json \
    --test_file $DATA_DIR/dev.json \
    --output_path $ROOT_DIR/predict.json \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --monitor val_loss --save_top_k 3 --every_n_train_steps 40 \
    --train_batchsize 16 --val_batchsize 16 \
    --max_length 512 \
    --learning_rate 1e-5 --weight_decay 0.1 --warmup_ratio 0.1 \
    --max_epochs 47 --gradient_clip_val 0.25 --val_check_interval 40 \
    --precision bf16
