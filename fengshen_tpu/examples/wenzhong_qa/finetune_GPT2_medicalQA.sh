#!/bin/bash
# hparams carried from reference: fengshen/examples/wenzhong_qa/finetune_GPT2_medicalQA.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Wenzhong-GPT2-3.5B}
DATA_DIR=${DATA_DIR:-./data/medicalQA}
python -m fengshen_tpu.examples.wenzhong_qa.finetune_wenzhong \
    --model_path $MODEL_PATH \
    --train_file $DATA_DIR/train.json \
    --val_file $DATA_DIR/dev.json \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize 1 \
    --max_seq_length 512 \
    --learning_rate 1e-5 --weight_decay 1e-2 \
    --adam_beta2 0.95 \
    --gradient_clip_val 1.0 \
    --precision bf16
