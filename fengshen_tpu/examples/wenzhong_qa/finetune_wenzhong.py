"""Wenzhong-GPT2 causal-LM finetune (QA).

Port of reference: fengshen/examples/wenzhong_qa/finetune_wenzhong.py —
GPT2 causal finetune on question/answer json with the
"Question:...Answer:..." format.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from fengshen_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from fengshen_tpu.trainer.modules import CausalLMModule


@dataclass
class WenzhongQACollator:
    tokenizer: Any
    max_seq_length: int = 512
    question_key: str = "question"
    answer_key: str = "answer"

    def __call__(self, samples: list[dict]) -> dict:
        batch = {"input_ids": [], "attention_mask": [], "labels": []}
        pad_id = self.tokenizer.pad_token_id or 0
        eos_id = self.tokenizer.eos_token_id
        for s in samples:
            text = f"Question:{s[self.question_key]} Answer:"
            q_ids = self.tokenizer.encode(text, add_special_tokens=False)
            a_ids = self.tokenizer.encode(str(s[self.answer_key]),
                                          add_special_tokens=False)
            if eos_id is not None:
                a_ids = a_ids + [eos_id]
            ids = (q_ids + a_ids)[: self.max_seq_length]
            labels = ([-100] * len(q_ids) + a_ids)[: self.max_seq_length]
            pad = self.max_seq_length - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["labels"].append(labels + [-100] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


class Wenzhong(CausalLMModule):
    def __init__(self, args, config: Optional[GPT2Config] = None):
        if config is None and getattr(args, "model_path", None):
            config = GPT2Config.from_pretrained(args.model_path)
        model = GPT2LMHeadModel(config)
        super().__init__(args, model, config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("Wenzhong QA")
        parser.add_argument("--max_seq_length", type=int, default=512)
        return parent_parser


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = Wenzhong.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = WenzhongQACollator(tokenizer,
                                  max_seq_length=args.max_seq_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = Wenzhong(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
