#!/bin/bash
# hparams carried from reference: fengshen/examples/wenzhong_qa/finetune_wenzhong.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
# ZeRO-3 + offload recipe -> --offload_optimizer (host-resident moments)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Wenzhong-GPT2-3.5B}
python -m fengshen_tpu.examples.wenzhong_qa.finetune_wenzhong \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize 1 \
    --max_seq_length 512 \
    --learning_rate 1e-5 --weight_decay 0.01 \
    --offload_optimizer \
    --gradient_clip_val 1.0 \
    --precision bf16
