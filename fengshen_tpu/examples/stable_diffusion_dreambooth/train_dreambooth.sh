#!/bin/bash
# Launcher for stable_diffusion_dreambooth.train (reference pattern: fengshen/examples/stable_diffusion_dreambooth/train.sh)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Taiyi-Stable-Diffusion-1B-Chinese-v0.1}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.stable_diffusion_dreambooth.train \
    --model_path $MODEL_PATH \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --instance_data_dir $INSTANCE_DIR --instance_prompt "$INSTANCE_PROMPT" --class_data_dir $CLASS_DIR --class_prompt "$CLASS_PROMPT" --with_prior_preservation --prior_loss_weight 1.0
