"""DreamBooth finetuning of Taiyi Stable Diffusion.

Port of the reference workload
(reference: fengshen/examples/stable_diffusion_dreambooth/train.py +
train_dreambooth.sh): a handful of instance images with a rare-token prompt
("a photo of sks dog") plus optional class images with the generic prompt,
trained jointly — instance MSE + `--prior_loss_weight` × class MSE — so the
subject binds to the rare token without forgetting the class
(prior-preservation loss).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.data.clip_dataloader.image_text import load_image
from fengshen_tpu.examples.finetune_taiyi_stable_diffusion.finetune import (
    TaiyiSDModule)
from fengshen_tpu.models.stable_diffusion import diffusion_loss

_IMG_EXTS = (".png", ".jpg", ".jpeg", ".webp", ".npy")


class DreamBoothDataset:
    """Pairs every instance image with (optionally) a class image, so each
    sample carries both halves of the prior-preservation objective
    (reference: train.py DreamBoothDataset)."""

    def __init__(self, instance_data_dir: str, instance_prompt: str,
                 class_data_dir: Optional[str] = None,
                 class_prompt: Optional[str] = None):
        self.instance_images = self._list(instance_data_dir)
        if not self.instance_images:
            raise ValueError(f"no images in {instance_data_dir}")
        self.instance_prompt = instance_prompt
        self.class_images = self._list(class_data_dir) if class_data_dir \
            else []
        self.class_prompt = class_prompt

    @staticmethod
    def _list(path: Optional[str]) -> list[str]:
        if not path or not os.path.isdir(path):
            return []
        return sorted(os.path.join(path, f) for f in os.listdir(path)
                      if f.lower().endswith(_IMG_EXTS))

    def __len__(self) -> int:
        return len(self.instance_images)

    def __getitem__(self, i: int) -> dict:
        sample = {"instance_image": self.instance_images[i],
                  "instance_prompt": self.instance_prompt}
        if self.class_images:
            sample["class_image"] = self.class_images[
                i % len(self.class_images)]
            sample["class_prompt"] = self.class_prompt
        return sample


@dataclass
class DreamBoothCollator:
    """Stacks instance rows first, then class rows, and records the split
    point so the loss can weight them differently."""

    tokenizer: Any
    image_size: int = 512
    max_length: int = 77

    def _encode(self, prompts, paths):
        enc = self.tokenizer(prompts, padding="max_length", truncation=True,
                             max_length=self.max_length,
                             return_tensors="np")
        images = np.stack([load_image(p, self.image_size) for p in paths])
        return (enc["input_ids"].astype(np.int32),
                enc["attention_mask"].astype(np.int32),
                (images * 2.0 - 1.0).astype(np.float32))

    def __call__(self, samples: list[dict]) -> dict:
        prompts = [s["instance_prompt"] for s in samples]
        paths = [s["instance_image"] for s in samples]
        has_prior = "class_image" in samples[0]
        if has_prior:
            prompts += [s["class_prompt"] for s in samples]
            paths += [s["class_image"] for s in samples]
        ids, mask, pixels = self._encode(prompts, paths)
        is_instance = np.zeros((len(prompts),), np.int32)
        is_instance[: len(samples)] = 1
        return {"input_ids": ids, "attention_mask": mask,
                "pixel_values": pixels, "is_instance": is_instance}


class DreamBoothModule(TaiyiSDModule):
    """Instance + prior-preservation diffusion loss
    (reference: train.py training_step with --with_prior_preservation)."""

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = TaiyiSDModule.add_module_specific_args(parent_parser)
        group = parser.add_argument_group("dreambooth")
        group.add_argument("--instance_data_dir", type=str, default=None)
        group.add_argument("--instance_prompt", type=str, default=None)
        group.add_argument("--class_data_dir", type=str, default=None)
        group.add_argument("--class_prompt", type=str, default=None)
        group.add_argument("--with_prior_preservation", action="store_true",
                           default=False)
        group.add_argument("--prior_loss_weight", type=float, default=1.0)
        group.add_argument(
            "--num_class_images", type=int, default=0,
            help="pre-generate class images with the frozen model until "
                 "class_data_dir holds this many (reference: "
                 "train_with_prior.sh --num_class_images=200)")
        group.add_argument("--class_gen_steps", type=int, default=50,
                           help="denoise steps for class-image pre-gen")
        return parser

    def training_loss(self, params, batch, rng):
        pred, latents, noise, timesteps = self._denoise_pred(params, batch,
                                                             rng)
        prediction_type = getattr(self.args, "prediction_type", "epsilon")
        if getattr(self.args, "with_prior_preservation", False) and \
                pred.shape[0] > 1:
            # instance rows vs class-prior rows weighted separately
            # (reference: train.py prior_loss_weight); target honors
            # --prediction_type, same as diffusion_loss
            if prediction_type == "v_prediction":
                target = self.scheduler.get_velocity(latents, noise,
                                                     timesteps)
            else:
                target = noise
            per_row = jnp.mean(jnp.square(
                pred.astype(jnp.float32) - target.astype(jnp.float32)),
                axis=(1, 2, 3))
            is_inst = batch["is_instance"].astype(bool)
            w_prior = getattr(self.args, "prior_loss_weight", 1.0)
            inst_loss = (per_row * is_inst).sum() / \
                jnp.maximum(is_inst.sum(), 1)
            prior_loss = (per_row * ~is_inst).sum() / \
                jnp.maximum((~is_inst).sum(), 1)
            return inst_loss + w_prior * prior_loss, {
                "instance_loss": inst_loss, "prior_loss": prior_loss}
        loss = diffusion_loss(pred, latents, noise, timesteps,
                              self.scheduler,
                              prediction_type=prediction_type)
        return loss, {}


def ensure_class_images(args, tokenizer, module) -> int:
    """Top up class_data_dir to --num_class_images by sampling the frozen
    model on --class_prompt (reference: stable_diffusion_dreambooth/
    train.py pre-generation loop before training with prior
    preservation). Returns how many images were generated."""
    import glob
    import os

    import jax

    from fengshen_tpu.models.stable_diffusion.sampling import text_to_image

    from fengshen_tpu.models.stable_diffusion.sampling import (
        init_sampling_params)

    os.makedirs(args.class_data_dir, exist_ok=True)
    have = len([p for ext in ("*.png", "*.jpg", "*.jpeg") for p in
                glob.glob(os.path.join(args.class_data_dir, ext))])
    need = max(int(args.num_class_images) - have, 0)
    if need == 0:
        return 0
    # the training init covers only the training submodules (VAE encode
    # + unet); sampling also needs the VAE decoder — init the full
    # sampling tree, then overlay the module's (possibly checkpoint-
    # imported) weights where paths coincide
    key = jax.random.PRNGKey(args.seed)
    params = init_sampling_params(module.model, key, args.image_size)

    def overlay(base, update):
        if not (isinstance(base, dict) and isinstance(update, dict)):
            return update
        out = dict(base)
        for k, v in update.items():
            out[k] = overlay(base[k], v) if k in base else v
        return out

    params = overlay(params, module.init_params(key))
    ids = jnp.asarray([tokenizer.encode(args.class_prompt)], jnp.int32)
    for i in range(need):
        img = text_to_image(module.model, params, ids,
                            image_size=args.image_size,
                            num_steps=args.class_gen_steps,
                            guidance_scale=1.0,
                            rng=jax.random.PRNGKey(args.seed + 1 + i))
        arr = (np.asarray(img[0]).clip(0, 1) * 255).astype(np.uint8)
        from PIL import Image
        Image.fromarray(arr).save(os.path.join(
            args.class_data_dir, f"class_gen_{have + i:05d}.png"))
    return need


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = DreamBoothModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    module = DreamBoothModule(args)
    if args.with_prior_preservation and args.num_class_images > 0 and \
            args.class_data_dir and args.class_prompt:
        n = ensure_class_images(args, tokenizer, module)
        if n:
            print(f"generated {n} class images into "
                  f"{args.class_data_dir}")
    dataset = DreamBoothDataset(
        args.instance_data_dir, args.instance_prompt,
        class_data_dir=args.class_data_dir if
        args.with_prior_preservation else None,
        class_prompt=args.class_prompt)
    collator = DreamBoothCollator(tokenizer, image_size=args.image_size,
                                  max_length=args.max_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args,
                                     datasets={"train": dataset})
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
