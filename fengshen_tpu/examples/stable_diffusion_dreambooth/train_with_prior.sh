#!/bin/bash
# hparams carried from reference: fengshen/examples/stable_diffusion_dreambooth/train_with_prior.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Taiyi-Stable-Diffusion-1B-Chinese-v0.1}
INSTANCE_DIR=${INSTANCE_DIR:-./instance_images}
CLASS_DIR=${CLASS_DIR:-./class_images_duck}
python -m fengshen_tpu.examples.stable_diffusion_dreambooth.train \
    --model_path $MODEL_PATH \
    --instance_data_dir $INSTANCE_DIR \
    --instance_prompt "一只鸭子" \
    --class_data_dir $CLASS_DIR \
    --class_prompt "鸭子" \
    --with_prior_preservation --prior_loss_weight 1.0 \
    --num_class_images 200 \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize 2 \
    --learning_rate 1e-6 \
    --precision bf16
