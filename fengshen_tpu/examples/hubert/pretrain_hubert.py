"""HuBERT masked-cluster-prediction pretraining.

Port of the reference workload
(reference: fengshen/examples/hubert/pretrain_hubert.py:19-230): fairseq
manifest + k-means labels via fengshen_tpu.data.hubert.HubertDataset, span
time-masking, and CE at masked frames (hubert_pretrain_loss).
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import jax.numpy as jnp

from fengshen_tpu.data.hubert import HubertCollator, HubertDataset
from fengshen_tpu.models.hubert import (HubertConfig, HubertModel,
                                        hubert_pretrain_loss)
from fengshen_tpu.trainer.module import TrainModule


class HubertPretrainModule(TrainModule):
    """reference: pretrain_hubert.py HubertLightning."""

    def __init__(self, args, config: Optional[HubertConfig] = None):
        super().__init__(args)
        if config is None:
            config = HubertConfig()
        self.config = config
        self.model = HubertModel(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("hubert pretrain")
        parser.add_argument("--data", type=str, default=None,
                            help="manifest dir with {split}.tsv")
        parser.add_argument("--label_dir", type=str, default=None)
        parser.add_argument("--labels", type=str, default="km")
        parser.add_argument("--label_rate", type=float, default=50.0)
        parser.add_argument("--sample_rate", type=int, default=16000)
        parser.add_argument("--max_sample_size", type=int, default=250000)
        parser.add_argument("--min_sample_size", type=int, default=2000)
        parser.add_argument("--pred_nomask_weight", type=float, default=0.0)
        return parent_parser

    def init_params(self, rng):
        wav = jnp.zeros((1, 400), jnp.float32)
        return self.model.init(rng, wav)["params"]

    def training_loss(self, params, batch, rng):
        logits, _ = self.model.apply(
            {"params": params}, batch["waveform"],
            mask_time_indices=batch["mask_time_indices"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_masked = hubert_pretrain_loss(
            logits, batch["cluster_ids"], batch["mask_time_indices"],
            unmasked_weight=getattr(self.args, "pred_nomask_weight", 0.0),
            frame_mask=batch.get("frame_mask"))
        acc = ((logits.argmax(-1) == batch["cluster_ids"]) *
               batch["mask_time_indices"]).sum() / jnp.maximum(n_masked, 1)
        return loss, {"masked_acc": acc, "n_masked": n_masked}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = HubertPretrainModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    config = HubertConfig()
    label_dir = args.label_dir or args.data
    datasets = {}
    for split in ("train", "valid"):
        manifest = os.path.join(args.data, f"{split}.tsv")
        label = os.path.join(label_dir, f"{split}.{args.labels}")
        if os.path.exists(manifest) and os.path.exists(label):
            key = "train" if split == "train" else "validation"
            datasets[key] = HubertDataset(
                manifest, label, sample_rate=args.sample_rate,
                label_rate=args.label_rate,
                max_sample_size=args.max_sample_size,
                min_keep_sample_size=args.min_sample_size)
    collator = HubertCollator(config.conv_layers,
                              mask_prob=config.mask_prob,
                              mask_length=config.mask_length,
                              pad_to=args.max_sample_size)
    datamodule = UniversalDataModule(collate_fn=collator, args=args,
                                     datasets=datasets)
    module = HubertPretrainModule(args, config)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
