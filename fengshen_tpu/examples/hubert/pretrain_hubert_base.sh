#!/bin/bash
# Launcher for hubert.pretrain_hubert (reference pattern: fengshen/examples/hubert/pretrain_hubert_base.sh)
MODEL_PATH=${MODEL_PATH:-none}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.hubert.pretrain_hubert \
    --model_path $MODEL_PATH \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --data $DATA_DIR --label_dir $LABEL_DIR --labels km --label_rate 50
