"""BERT whole-word-masking pretraining (jieba n-gram spans).

Port of the reference workload
(reference: fengshen/examples/pretrain_bert/pretrain_bert.py:36-278): jieba
word segmentation over the raw text, n-gram span selection with p(n) ∝ 1/n,
80/10/10 mask/keep/random replacement, and an MLM objective on BertForMaskedLM.
Run:

    python -m fengshen_tpu.examples.pretrain_bert.pretrain_bert \
        --train_file corpus.json --model_path <bert-dir> --max_steps 10000 ...
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.bert import BertConfig, BertForMaskedLM
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class WWMBertCollator:
    """jieba whole-word n-gram masking (reference: pretrain_bert.py:36-130
    DataCollate: word_cuter=jieba.cut, ngram pvals 1/n, token_process
    80/10/10)."""

    tokenizer: Any
    max_seq_length: int = 512
    mask_rate: float = 0.15
    max_ngram: int = 3
    content_key: str = "text"
    seed: int = 42

    def __post_init__(self):
        try:
            import jieba
            self.word_cuter = jieba.lcut
        except ImportError:  # pragma: no cover - jieba is available in CI
            self.word_cuter = lambda t: list(t)
        self.np_rng = np.random.RandomState(self.seed)
        self.ngrams = np.arange(1, self.max_ngram + 1)
        pvals = 1.0 / np.arange(1, self.max_ngram + 1)
        self.pvals = pvals / pvals.sum()
        self.vocab_length = len(self.tokenizer)

    def _token_process(self, token_id: int) -> int:
        """80% [MASK] / 10% keep / 10% random
        (reference: pretrain_bert.py:52-59)."""
        r = self.np_rng.random()
        if r <= 0.8:
            return self.tokenizer.mask_token_id
        if r <= 0.9:
            return token_id
        return int(self.np_rng.randint(1, self.vocab_length))

    def __call__(self, samples: list[dict]) -> dict:
        max_len = self.max_seq_length
        batch = {"input_ids": [], "attention_mask": [], "token_type_ids": [],
                 "labels": []}
        for sample in samples:
            words = self.word_cuter(sample[self.content_key])
            mask_ids: list[int] = []
            labels: list[int] = []
            i = 0
            while i < len(words):
                rand = self.np_rng.random()
                if rand > self.mask_rate or len(words[i]) >= 4:
                    # unmasked word
                    for tok in self.tokenizer.encode(
                            words[i], add_special_tokens=False):
                        mask_ids.append(tok)
                        labels.append(-100)
                    i += 1
                    continue
                # masked n-gram span (reference: pretrain_bert.py:85-105)
                n = int(self.np_rng.choice(self.ngrams, p=self.pvals))
                span = words[i: i + n]
                for word in span:
                    for tok in self.tokenizer.encode(
                            word, add_special_tokens=False):
                        mask_ids.append(self._token_process(tok))
                        labels.append(tok)
                i += n
            cls, sep = self.tokenizer.cls_token_id, self.tokenizer.sep_token_id
            pad_id = self.tokenizer.pad_token_id or 0
            ids = [cls] + mask_ids[: max_len - 2] + [sep]
            lab = [-100] + labels[: max_len - 2] + [-100]
            pad = max_len - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            batch["token_type_ids"].append([0] * max_len)
            batch["labels"].append(lab + [-100] * pad)
        return {k: np.asarray(v) for k, v in batch.items()}


class BertPretrainModule(TrainModule):
    """MLM loss on BertForMaskedLM (reference: pretrain_bert.py:160-210)."""

    def __init__(self, args, config: Optional[BertConfig] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = BertConfig.from_pretrained(args.model_path)
        self.config = config
        self.model = BertForMaskedLM(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("Bert pretrain")
        parser.add_argument("--masked_lm_prob", type=float, default=0.15)
        parser.add_argument("--max_ngram", type=int, default=3)
        parser.add_argument("--max_seq_length", type=int, default=512)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits,
                                                      batch["labels"])
        valid = batch["labels"] != -100
        acc = ((logits.argmax(-1) == batch["labels"]) * valid).sum() / \
            jnp.maximum(valid.sum(), 1)
        return loss, {"mlm_acc": acc, "n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = BertPretrainModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = WWMBertCollator(tokenizer,
                               max_seq_length=args.max_seq_length,
                               mask_rate=args.masked_lm_prob,
                               max_ngram=args.max_ngram)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = BertPretrainModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
