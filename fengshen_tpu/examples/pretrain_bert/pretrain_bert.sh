#!/bin/bash
# Launcher for pretrain_bert.pretrain_bert (reference pattern: fengshen/examples/pretrain_bert/pretrain_bert.sh)
# Multi-host TPU: run this script on every host with JAX_COORDINATOR_ADDRESS
# set (see docs/multihost.md); single host needs no extra flags.
MODEL_PATH=${MODEL_PATH:-bert-base-chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/pretrain_bert.pretrain_bert}

python -m fengshen_tpu.examples.pretrain_bert.pretrain_bert \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-32} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --max_seq_length 512 --masked_lm_prob 0.15
