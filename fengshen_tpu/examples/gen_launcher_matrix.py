"""Generate the reproducibility launcher matrix (VERDICT r2 item 6).

The reference ships its hyperparameters as per-task shell scripts — they
are the reproducibility artifact (reference: fengshen/examples/
zen2_finetune/*.sh 22 configs, zen1_finetune/*.sh, pretrain_t5/*.sh
57M→10B, clue1.1/). This generator re-emits that matrix against OUR
drivers and flags, with the task hyperparameters (labels, batch sizes,
sequence lengths, learning rates) carried over from the reference
shells verbatim. Run `python -m fengshen_tpu.examples.gen_launcher_matrix`
to regenerate; tests/test_launcher_matrix.py smoke-parses every emitted
flag against the target driver's argparse.
"""

from __future__ import annotations

import os

HERE = os.path.dirname(__file__)

HEADER = """#!/bin/bash
# {title}
# hparams carried from reference: fengshen/examples/{ref}
# TPU: single host by default; scale via the mesh flags
# (--tensor_model_parallel_size / --fsdp_parallel_size) and
# launchers/slurm_multihost.sh or launchers/gke_tpu_job.yaml.
set -euo pipefail

MODEL_PATH=${{MODEL_PATH:-{model}}}
DATA_DIR=${{DATA_DIR:-./data/{task}}}
ROOT_DIR=${{ROOT_DIR:-./workdir/$(basename $0 .sh)}}
mkdir -p $ROOT_DIR
"""

# ---------------------------------------------------------------- zen --

# (task, num_labels, batch_base, batch_large, max_seq, lr)
ZEN2_SEQ_TASKS = [
    ("afqmc", 2, 32, 32, 128, "2e-5"),
    ("cmnli", 3, 64, 32, 128, "2e-5"),
    ("iflytek", 119, 32, 32, 128, "2e-5"),
    ("ocnli", 3, 32, 32, 128, "2e-5"),
    ("tnews", 15, 32, 32, 128, "2e-5"),
]
# (task, batch, max_seq, lr)
ZEN2_NER_TASKS = [
    ("cluener", 32, 256, "3e-5"),
    ("cmeee", 16, 512, "3e-5"),
    ("msra", 32, 256, "3e-5"),
    ("ontonotes4", 32, 256, "3e-5"),
    ("resume", 32, 256, "3e-5"),
    ("weibo", 32, 256, "3e-5"),
]
ZEN2_MODELS = {"base": "IDEA-CCNL/Erlangshen-ZEN2-345M-Chinese",
               "large": "IDEA-CCNL/Erlangshen-ZEN2-668M-Chinese"}


def _zen2_seq_shell(size, task, labels, batch, seq, lr):
    body = HEADER.format(
        title=f"ZEN2-{size} {task} classification finetune",
        ref=f"zen2_finetune/fs_zen2_{size}_{task}.sh",
        model=ZEN2_MODELS[size], task=task)
    body += f"""
python -m fengshen_tpu.examples.zen2_finetune.fengshen_sequence_level_ft_task \\
    --model_path $MODEL_PATH \\
    --train_file $DATA_DIR/train.json \\
    --val_file $DATA_DIR/dev.json \\
    --test_file $DATA_DIR/test1.1.json \\
    --default_root_dir $ROOT_DIR \\
    --save_ckpt_path $ROOT_DIR/ckpt \\
    --load_ckpt_path $ROOT_DIR/ckpt \\
    --monitor val_acc --mode max --save_top_k 3 \\
    --train_batchsize {batch} \\
    --val_batchsize 16 \\
    --max_seq_length {seq} \\
    --num_labels {labels} \\
    --learning_rate {lr} \\
    --weight_decay 0.01 \\
    --warmup_ratio 0.01 \\
    --max_epochs 7 \\
    --precision bf16 \\
    --seed 1234
"""
    return body


def _zen2_ner_shell(size, task, batch, seq, lr):
    body = HEADER.format(
        title=f"ZEN2-{size} {task} NER finetune",
        ref=f"zen2_finetune/ner_zen2_{size}_{task}.sh",
        model=ZEN2_MODELS[size], task=task)
    body += f"""
python -m fengshen_tpu.examples.zen2_finetune.fengshen_token_level_ft_task \\
    --model_path $MODEL_PATH \\
    --data_dir $DATA_DIR \\
    --default_root_dir $ROOT_DIR \\
    --save_ckpt_path $ROOT_DIR/ckpt \\
    --load_ckpt_path $ROOT_DIR/ckpt \\
    --monitor val_f1 --mode max --save_top_k 3 \\
    --train_batchsize {batch} \\
    --val_batchsize 16 \\
    --max_seq_length {seq} \\
    --learning_rate {lr} \\
    --weight_decay 0.01 \\
    --warmup_ratio 0.01 \\
    --max_epochs 5 \\
    --precision bf16 \\
    --seed 1234
"""
    return body


# ----------------------------------------------------------------- t5 --

# size -> (d_model, d_ff, num_layers, num_heads, micro_batch, tp, fsdp)
# dims follow the public Randeng-T5-Char family scale points; batch and
# lr/warmup come from the reference shells (MICRO_BATCH_SIZE, deepspeed
# scheduler warmup_max_lr 1e-4 over 10k steps)
T5_SCALES = {
    "57M": (512, 1024, 8, 6, 64, 1, 1),
    "700M": (1024, 2816, 24, 16, 8, 1, 8),
    "large": (1024, 2816, 24, 16, 8, 1, 8),
    "10B": (4096, 10240, 24, 64, 1, 8, 4),
}


def _t5_shell(size):
    d_model, d_ff, layers, heads, micro, tp, fsdp = T5_SCALES[size]
    name = ("pretrain_randeng_t5_large" if size == "large" else
            f"pretrain_randeng_t5_char_{size}")
    body = HEADER.format(
        title=f"Randeng-T5 {size} span-corruption pretrain",
        ref=f"pretrain_t5/{name}.sh",
        model=f"./randeng_t5_char_{size}", task="wudao_180g")
    body += f"""
# model config for this scale point (written once into the workdir)
if [ ! -f $MODEL_PATH/config.json ]; then
  mkdir -p $MODEL_PATH
  cat > $MODEL_PATH/config.json << EOF
{{"vocab_size": 32596, "d_model": {d_model}, "d_ff": {d_ff},
 "num_layers": {layers}, "num_decoder_layers": {layers},
 "num_heads": {heads}, "dropout_rate": 0.1, "model_type": "t5"}}
EOF
fi

python -m fengshen_tpu.examples.pretrain_t5.pretrain_t5 \\
    --model_path $MODEL_PATH \\
    --train_file $DATA_DIR/train.json \\
    --default_root_dir $ROOT_DIR \\
    --save_ckpt_path $ROOT_DIR/ckpt \\
    --load_ckpt_path $ROOT_DIR/ckpt \\
    --train_batchsize {micro} \\
    --max_seq_length 512 \\
    --learning_rate 1e-4 \\
    --min_learning_rate 1e-5 \\
    --warmup_steps 10000 \\
    --max_steps 100000 \\
    --every_n_train_steps 5000 \\
    --tensor_model_parallel_size {tp} \\
    --fsdp_parallel_size {fsdp} \\
    --precision bf16 \\
    --seed 1234
"""
    return body


# ------------------------------------------------------------- clue1.1 --

def _clue_unimc_shell():
    return """#!/bin/bash
# CLUE1.1 leaderboard recipe via UniMC (reference:
# fengshen/examples/clue1.1/run_clue_unimc.sh — tnews/afqmc/iflytek/
# wsc/ocnli/csl/chid/c3 as unified multiple choice)
set -euo pipefail

TASK=${TASK:-tnews}
DATA_DIR=${DATA_DIR:-./data/$TASK}
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-UniMC-RoBERTa-110M-Chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/clue11_unimc_$TASK}
mkdir -p $ROOT_DIR

python -m fengshen_tpu.examples.clue1_1.run_clue_unimc \\
    --task $TASK \\
    --data_dir $DATA_DIR \\
    --model_path $MODEL_PATH \\
    --default_root_dir $ROOT_DIR \\
    --save_ckpt_path $ROOT_DIR/ckpt \\
    --load_ckpt_path $ROOT_DIR/ckpt \\
    --train_batchsize 16 \\
    --max_length 512 \\
    --learning_rate 2e-5 \\
    --max_epochs 7 \\
    --precision bf16 \\
    --output_path $ROOT_DIR/${TASK}_predict.json
"""


def _clue_ubert_shell():
    return """#!/bin/bash
# CLUE1.1 extraction-style recipe via UBERT (reference:
# fengshen/examples/clue1.1/run_clue_ubert.sh)
set -euo pipefail

TASK=${TASK:-cmrc}
DATA_DIR=${DATA_DIR:-./data/$TASK}
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-Ubert-110M-Chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/clue11_ubert_$TASK}
mkdir -p $ROOT_DIR

python -m fengshen_tpu.examples.clue1_1.run_clue_ubert \\
    --task $TASK \\
    --data_dir $DATA_DIR \\
    --model_path $MODEL_PATH \\
    --default_root_dir $ROOT_DIR \\
    --save_ckpt_path $ROOT_DIR/ckpt \\
    --load_ckpt_path $ROOT_DIR/ckpt \\
    --train_batchsize 8 \\
    --max_length 512 \\
    --learning_rate 2e-5 \\
    --max_epochs 5 \\
    --precision bf16 \\
    --output_path $ROOT_DIR/${TASK}_predict.json
"""


def main():
    written = []

    def emit(reldir, name, content):
        path = os.path.join(HERE, reldir, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(content)
        os.chmod(path, 0o755)
        written.append(os.path.relpath(path, HERE))

    for size in ("base", "large"):
        for task, labels, b_base, b_large, seq, lr in ZEN2_SEQ_TASKS:
            batch = b_base if size == "base" else b_large
            emit("zen2_finetune", f"fs_zen2_{size}_{task}.sh",
                 _zen2_seq_shell(size, task, labels, batch, seq, lr))
        for task, batch, seq, lr in ZEN2_NER_TASKS:
            emit("zen2_finetune", f"ner_zen2_{size}_{task}.sh",
                 _zen2_ner_shell(size, task, batch, seq, lr))

    # zen1: the reference ships one classification + one NER shell
    # (fs_zen1_tnews.sh already exists); NER hparams from the reference
    # ner_zen1_ontonotes4.sh: batch 64, max_seq 128, lr 3e-5
    zen1_ner = _zen2_ner_shell("base", "ontonotes4", 64, 128, "3e-5")
    zen1_ner = zen1_ner.replace(
        "ZEN2-base ontonotes4 NER finetune", "ZEN1 ontonotes4 NER finetune"
    ).replace(
        "zen2_finetune/ner_zen2_base_ontonotes4.sh",
        "zen1_finetune/ner_zen1_ontonotes4.sh"
    ).replace("IDEA-CCNL/Erlangshen-ZEN2-345M-Chinese",
              "IDEA-CCNL/Erlangshen-ZEN1-224M-Chinese"
    ).replace("zen2_finetune.fengshen_token_level_ft_task",
              "zen1_finetune.fengshen_token_level_ft_task")
    emit("zen1_finetune", "ner_zen1_ontonotes4.sh", zen1_ner)

    for size in T5_SCALES:
        name = ("pretrain_randeng_t5_large.sh" if size == "large" else
                f"pretrain_randeng_t5_char_{size}.sh")
        emit("pretrain_t5", name, _t5_shell(size))

    emit("clue1_1", "run_clue_unimc.sh", _clue_unimc_shell())
    emit("clue1_1", "run_clue_ubert.sh", _clue_ubert_shell())
    print(f"wrote {len(written)} launchers")
    return written


if __name__ == "__main__":
    main()
