"""Randeng-Pegasus gap-sentence-generation (GSG) pretraining.

Port of the reference workload
(reference: fengshen/examples/pegasus/pretrain_pegasus.py:30-181 +
data_utils.py:99-319): split the document into sentences, score each
sentence against the rest of the document, select the top `gsg_ratio`
sentences as the pseudo-summary, replace them with a mask sentinel in the
source, and train the seq2seq model to generate them. The reference scores
with the `rouge` package (data_utils.py:181-199); here the score is a
dependency-free unigram-F1 against the remaining text — same selection
principle, no native rouge dependency.
"""

from __future__ import annotations

import argparse
import re
from collections import Counter
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.examples.summary.seq2seq_summary import Seq2SeqCollator
from fengshen_tpu.models.pegasus import (PegasusConfig,
                                         PegasusForConditionalGeneration)
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule

_SENT_SPLIT = re.compile(r"([。！？!?；;\n]+)")


def split_sentences(text: str) -> list[str]:
    """Sentence segmentation on Chinese terminal punctuation
    (reference: data_utils.py:99-113 text_segmentate)."""
    parts = _SENT_SPLIT.split(text)
    sents = []
    for i in range(0, len(parts) - 1, 2):
        s = (parts[i] + parts[i + 1]).strip()
        if s:
            sents.append(s)
    if len(parts) % 2 == 1 and parts[-1].strip():
        sents.append(parts[-1].strip())
    return sents


def unigram_f1(source: str, target: str) -> float:
    """Unigram-overlap F1 (character level) — the GSG selection score
    (substitutes reference data_utils.py:181-199 compute_rouge)."""
    a, b = Counter(source), Counter(target)
    overlap = sum((a & b).values())
    if overlap == 0:
        return 0.0
    p, r = overlap / max(sum(a.values()), 1), overlap / max(sum(b.values()), 1)
    return 2 * p * r / (p + r)


def gap_sentence_ids(sents: list[str], ratio: float) -> list[int]:
    """Pick the sentences most representative of the rest of the document
    (reference: data_utils.py pseudo_summary construction)."""
    n_select = max(1, int(len(sents) * ratio))
    scores = []
    for i, s in enumerate(sents):
        rest = "".join(sents[:i] + sents[i + 1:])
        scores.append(unigram_f1(s, rest))
    return sorted(np.argsort(scores)[::-1][:n_select].tolist())


@dataclass
class PegasusGSGCollator(Seq2SeqCollator):
    """document → (masked source, pseudo-summary target)
    (reference: pretrain_pegasus.py:40-88); batching inherited from
    Seq2SeqCollator (decoder_start_token_id = pad, the pegasus convention —
    set in main), only the GSG split here."""

    gsg_ratio: float = 0.25
    content_key: str = "text"
    mask_sentence_token: str = "[MASK]"

    def _split(self, sample: dict) -> tuple[list[str], set[int]]:
        # source_text and target_text are called back-to-back per sample;
        # memoise the quadratic GSG scoring so it runs once, not twice.
        # Hold the sample OBJECT (not its id) so a recycled address can
        # never alias a stale entry.
        if getattr(self, "_memo_sample", None) is sample:
            return self._memo_val
        sents = split_sentences(sample[self.content_key])
        if not sents:
            sents = [sample[self.content_key] or self.mask_sentence_token]
        result = (sents, set(gap_sentence_ids(sents, self.gsg_ratio)))
        self._memo_sample, self._memo_val = sample, result
        return result

    def source_text(self, sample: dict) -> str:
        sents, selected = self._split(sample)
        return "".join(self.mask_sentence_token if i in selected else s
                       for i, s in enumerate(sents))

    def target_text(self, sample: dict) -> str:
        sents, selected = self._split(sample)
        return "".join(s for i, s in enumerate(sents) if i in selected)


class PegasusPretrainModule(TrainModule):
    """GSG seq2seq loss (reference: pretrain_pegasus.py:90-140)."""

    def __init__(self, args, config: Optional[PegasusConfig] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = PegasusConfig.from_pretrained(args.model_path)
        self.config = config
        self.model = PegasusForConditionalGeneration(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("Pegasus pretrain")
        parser.add_argument("--max_seq_length", type=int, default=512)
        parser.add_argument("--max_target_length", type=int, default=128)
        parser.add_argument("--gsg_ratio", type=float, default=0.25)
        return parent_parser

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            batch["decoder_input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits,
                                                      batch["labels"])
        return loss, {"n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = PegasusPretrainModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = PegasusGSGCollator(
        tokenizer, max_src_length=args.max_seq_length,
        max_tgt_length=args.max_target_length,
        decoder_start_token_id=tokenizer.pad_token_id or 0,
        gsg_ratio=args.gsg_ratio)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = PegasusPretrainModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
