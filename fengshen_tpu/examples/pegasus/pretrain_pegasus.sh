#!/bin/bash
# Launcher for pegasus.pretrain_pegasus (reference pattern: fengshen/examples/pegasus/pretrain_pegasus.sh)
# Multi-host TPU: run this script on every host with JAX_COORDINATOR_ADDRESS
# set (see docs/multihost.md); single host needs no extra flags.
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-Pegasus-238M-Chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/pegasus.pretrain_pegasus}

python -m fengshen_tpu.examples.pegasus.pretrain_pegasus \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-32} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --max_seq_length 512 --gsg_ratio 0.25
