#!/bin/bash
# hparams carried from reference: fengshen/examples/pretrain_t5/convert_ckpt_randeng_t5_char.sh
# DeepSpeed mp_rank .pt -> bare pytorch_model.bin (strip module.model.)
set -euo pipefail
BIN_DIR=${BIN_DIR:-./randeng_t5_char_57M}
mkdir -p $BIN_DIR
python -m fengshen_tpu.examples.pretrain_t5.convert_ckpt_to_bin \
    --ckpt_path ${CKPT_PATH:-./ckpt/last.ckpt/checkpoint/mp_rank_00_model_states.pt} \
    --bin_path $BIN_DIR/pytorch_model.bin \
    --rm_prefix module.model.
