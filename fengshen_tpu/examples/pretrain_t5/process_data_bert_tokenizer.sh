#!/bin/bash
# hparams carried from reference: fengshen/examples/pretrain_t5/process_data_bert_tokenizer.sh
# one-off corpus tokenization with the char-level Randeng vocab
set -euo pipefail
python -m fengshen_tpu.examples.pretrain_t5.process_data \
    --tokenizer_type bert_tokenizer \
    --train_data_path ${TRAIN_DATA_PATH:-wudao_180g} \
    --train_split_size 0.999 \
    --max_seq_length 512 \
    --preprocessing_num_workers 100 \
    --saved_data_shards 800 \
    --saved_train_data_path ${SAVED_TRAIN:-./tokenized/train} \
    --saved_test_data_path ${SAVED_TEST:-./tokenized/test} \
    --pretrained_model_path ${MODEL_PATH:-IDEA-CCNL/Randeng-T5-Char-57M-Chinese}
