#!/bin/bash
# Eval-only sweep over the validation file with the last checkpoint
# (reference: fengshen/examples/pretrain_t5/pretrain_mt5_small_predict.sh
# --do_eval_only).
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-T5-77M}
ROOT_DIR=${ROOT_DIR:-./workdir/pretrain_t5.pretrain_t5}

python -m fengshen_tpu.examples.pretrain_t5.pretrain_t5 \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --val_file ${VAL_FILE:-val.json} \
    --do_eval_only \
    --default_root_dir $ROOT_DIR \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --val_batchsize ${BATCH:-32} \
    --precision bf16 \
    --max_seq_length 512 --noise_density 0.15
