"""Offline corpus tokenization for T5 pretraining.

Port of reference: fengshen/examples/pretrain_t5/process_data.py
(driven by process_data_bert_tokenizer.sh): tokenize a text corpus once,
split train/test by ``--train_split_size``, and save sharded tokenized
data so the pretrain run streams pre-encoded ids instead of re-running
the tokenizer per epoch.

TPU-native: shards are written as ``.npy`` object arrays of int32 id
lists (mmap-friendly), not HF `datasets.save_to_disk` arrow dirs; the
reference flag surface is preserved.
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np


def iter_texts(path: str, text_column: str):
    """Rows from a jsonl file, a directory of jsonl files, or a plain
    text file (one doc per line)."""
    paths = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith((".json", ".jsonl", ".txt")):
                paths.append(os.path.join(path, name))
    else:
        paths = [path]
    for p in paths:
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                if line.startswith("{"):
                    try:
                        yield json.loads(line).get(text_column, "")
                        continue
                    except json.JSONDecodeError:
                        pass
                yield line


def save_shards(rows: list, out_dir: str, n_shards: int) -> int:
    os.makedirs(out_dir, exist_ok=True)
    n_shards = max(1, min(n_shards, len(rows) or 1))
    for i in range(n_shards):
        shard = rows[i::n_shards]
        arr = np.empty(len(shard), dtype=object)
        for j, ids in enumerate(shard):
            arr[j] = np.asarray(ids, np.int32)
        np.save(os.path.join(out_dir, f"shard_{i:05d}.npy"), arr,
                allow_pickle=True)
    return n_shards


def main(argv=None):
    parser = argparse.ArgumentParser("Pretrain Unsupervise.")
    parser.add_argument("--train_data_path", default=None, type=str)
    parser.add_argument("--preprocessing_num_workers", default=30,
                        type=int)
    parser.add_argument("--saved_data_shards", default=800, type=int)
    parser.add_argument("--saved_train_data_path", default=None, type=str)
    parser.add_argument("--saved_test_data_path", default=None, type=str)
    parser.add_argument("--max_seq_length", default=512, type=int)
    parser.add_argument("--train_split_size", default=0.999, type=float)
    parser.add_argument("--pretrained_model_path", default=None, type=str)
    parser.add_argument("--tokenizer_type", default="t5_tokenizer",
                        choices=["t5_tokenizer", "bert_tokenizer"])
    parser.add_argument("--text_column_name", default="text")
    parser.add_argument("--remove_columns", nargs="+", default=[])
    args = parser.parse_args(argv)

    if args.tokenizer_type == "bert_tokenizer":
        from fengshen_tpu.models.t5 import T5Tokenizer
        tokenizer = T5Tokenizer.from_pretrained(args.pretrained_model_path)
    else:
        from transformers import AutoTokenizer
        tokenizer = AutoTokenizer.from_pretrained(
            args.pretrained_model_path)

    rows = []
    for text in iter_texts(args.train_data_path, args.text_column_name):
        ids = tokenizer.encode(text, add_special_tokens=False,
                               truncation=True,
                               max_length=args.max_seq_length)
        if ids:
            rows.append(ids)

    split = int(len(rows) * args.train_split_size)
    train, test = rows[:split], rows[split:]
    n_train = save_shards(train, args.saved_train_data_path,
                          args.saved_data_shards)
    n_test = save_shards(test, args.saved_test_data_path,
                         max(1, args.saved_data_shards // 100))
    print(f"train: {len(train)} docs / {n_train} shards -> "
          f"{args.saved_train_data_path}")
    print(f"test:  {len(test)} docs / {n_test} shards -> "
          f"{args.saved_test_data_path}")


if __name__ == "__main__":
    main()
