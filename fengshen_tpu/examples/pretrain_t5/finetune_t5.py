"""UniMC-format multiple-choice finetune over T5 (Randeng-T5-Char 57M).

Port of reference: fengshen/examples/pretrain_t5/finetune_t5.py +
data/t5_dataloader/t5_datasets.py:438-505 TaskT5Dataset (driven by
finetune_unimc_randeng_t5_char_57M.sh): each UniMC row
``{texta, textb, question, choice, answer}`` becomes
``question + '，'.join(choice) + '。' + texta [+ textb]`` → the answer
text, trained with seq2seq CE.

TPU-native evaluation: the reference's validation runs HF
``generate(force_words_ids=answer_tokens, num_beams=2)`` — a dynamic
constrained beam that does not map to static-shape XLA. The equivalent
choice-restricted decision here scores each option's token sequence by
teacher-forced log-likelihood in ONE jitted batched pass and takes the
argmax; same decision rule over the same candidate set, no dynamic
control flow.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule

MAX_ANSWER_LEN = 16  # reference: t5_datasets.py:470 decode max_length=16


class TaskT5Dataset:
    """reference: t5_datasets.py:438-460."""

    def __init__(self, data_path: str, args):
        self.max_length = args.max_seq_length
        with open(data_path, encoding="utf8") as f:
            self.data = [json.loads(line) for line in f if line.strip()]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, index):
        return self.data[index]


def encode_text(item: dict) -> str:
    """reference: t5_datasets.py:462-466."""
    if item.get("textb"):
        return (item["question"] + "，".join(item["choice"]) + "。" +
                str(item["texta"]) + str(item["textb"]))
    return (str(item["question"]) + "，".join(item["choice"]) + "。" +
            str(item["texta"]))


@dataclass
class TaskT5Collator:
    tokenizer: Any
    max_seq_length: int = 512
    decoder_start_token_id: int = 0
    #: static option count per batch (CLUE tnews has 15, iflytek 119 —
    #: size it to the task; one fixed shape keeps the jit cache at 1)
    max_choices: int = 16

    def _encode_answer(self, text: str) -> list[int]:
        ids = self.tokenizer.encode(text, add_special_tokens=False)
        eos = self.tokenizer.eos_token_id
        if eos is not None:
            ids = ids[: MAX_ANSWER_LEN - 1] + [eos]
        return ids[:MAX_ANSWER_LEN]

    def __call__(self, samples: list[dict]) -> dict:
        pad = self.tokenizer.pad_token_id or 0
        batch = {"input_ids": [], "attention_mask": [],
                 "decoder_input_ids": [], "labels": [],
                 "choice_ids": [], "choice_mask": [], "label_idx": []}
        for item in samples:
            enc = self.tokenizer(
                encode_text(item), max_length=self.max_seq_length,
                padding="max_length", truncation=True)
            batch["input_ids"].append(enc["input_ids"])
            batch["attention_mask"].append(enc["attention_mask"])
            tgt = self._encode_answer(item.get("answer", ""))
            dec_in = [self.decoder_start_token_id] + tgt[:-1]
            pad_t = MAX_ANSWER_LEN - len(tgt)
            batch["decoder_input_ids"].append(dec_in + [pad] * pad_t)
            batch["labels"].append(tgt + [-100] * pad_t)
            # all options, for the choice-restricted eval
            cids = np.full((self.max_choices, MAX_ANSWER_LEN), -100,
                           np.int32)
            cmask = np.zeros((self.max_choices,), np.int32)
            for c, choice in enumerate(item["choice"][: self.max_choices]):
                ids = self._encode_answer(choice)
                cids[c, : len(ids)] = ids
                cmask[c] = 1
            batch["choice_ids"].append(cids)
            batch["choice_mask"].append(cmask)
            batch["label_idx"].append(int(item.get("label", 0)))
        return {k: np.asarray(v) for k, v in batch.items()}


class MT5FinetuneModule(TrainModule):
    """reference: finetune_t5.py:14-103 MT5FinetuneModel."""

    def __init__(self, args, model=None, config=None):
        super().__init__(args)
        from fengshen_tpu.models.t5 import (T5Config,
                                            T5ForConditionalGeneration)
        if config is None:
            config = T5Config.from_pretrained(args.pretrained_model_path)
        self.config = config
        self.model = model or T5ForConditionalGeneration(config)

    @staticmethod
    def add_model_specific_args(parent_args):
        parser = parent_args.add_argument_group("BaseModel")
        parser.add_argument("--keep_tokens_path", default=None, type=str)
        parser.add_argument("--max_seq_length", default=512, type=int)
        parser.add_argument(
            "--tokenizer_type", default="t5_tokenizer", type=str,
            choices=["t5_tokenizer", "bert_tokenizer"])
        parser.add_argument("--pretrained_model_path", default=None,
                            type=str)
        parser.add_argument("--train_data_path", default=None, type=str)
        parser.add_argument("--valid_data_path", default=None, type=str)
        parser.add_argument("--max_choices", default=16, type=int)
        return parent_args

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids, ids)["params"]

    def _loss(self, params, batch, rng=None):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            batch["decoder_input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=rng is None,
            rngs={"dropout": rng} if rng is not None else None)
        return vocab_parallel_cross_entropy(logits, batch["labels"])

    def training_loss(self, params, batch, rng):
        loss, n = self._loss(params, batch, rng)
        return loss, {"n_tokens": n}

    def validation_loss(self, params, batch, rng):
        loss, _ = self._loss(params, batch)
        # choice-restricted accuracy: teacher-forced log-likelihood per
        # option (the static-shape counterpart of the reference's
        # force_words_ids beam)
        B, C, L = batch["choice_ids"].shape
        rep = lambda x: jnp.repeat(x, C, axis=0)  # noqa: E731
        choice = batch["choice_ids"].reshape(B * C, L)
        pad = 0
        # the SAME start token training shifts with — a nonzero
        # decoder_start_token_id otherwise mis-scores every option
        start = jnp.full((B * C, 1), self.config.decoder_start_token_id,
                         choice.dtype)
        dec_in = jnp.concatenate(
            [start,
             jnp.where(choice[:, :-1] < 0, pad, choice[:, :-1])], axis=1)
        logits = self.model.apply(
            {"params": params}, rep(batch["input_ids"]), dec_in,
            attention_mask=rep(batch["attention_mask"]),
            deterministic=True)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok_lp = jnp.take_along_axis(
            logp, jnp.where(choice < 0, 0, choice)[..., None],
            axis=-1)[..., 0]
        valid = (choice >= 0).astype(jnp.float32)
        scores = (tok_lp * valid).sum(-1) / jnp.maximum(valid.sum(-1), 1)
        scores = scores.reshape(B, C)
        scores = jnp.where(batch["choice_mask"] > 0, scores, -1e9)
        acc = (scores.argmax(-1) == batch["label_idx"]).mean()
        return loss, {"cond_acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser("Finetune T5 (UniMC format)")
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = MT5FinetuneModule.add_model_specific_args(parser)
    args = parser.parse_args(argv)

    if args.tokenizer_type == "bert_tokenizer":
        from fengshen_tpu.models.t5 import T5Tokenizer
        tokenizer = T5Tokenizer.from_pretrained(args.pretrained_model_path)
    else:
        from transformers import AutoTokenizer
        tokenizer = AutoTokenizer.from_pretrained(
            args.pretrained_model_path)

    module = MT5FinetuneModule(args)
    collator = TaskT5Collator(
        tokenizer, max_seq_length=args.max_seq_length,
        decoder_start_token_id=module.config.decoder_start_token_id,
        max_choices=args.max_choices)
    datasets = {"train": TaskT5Dataset(args.train_data_path, args)}
    if args.valid_data_path:
        datasets["validation"] = TaskT5Dataset(args.valid_data_path, args)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args,
                                     datasets=datasets)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
