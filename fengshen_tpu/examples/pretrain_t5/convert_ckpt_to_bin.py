"""DeepSpeed/Lightning .ckpt → bare pytorch_model.bin.

Port of reference: fengshen/examples/pretrain_t5/convert_ckpt_to_bin.py
:13-34 (driven by convert_ckpt_randeng_t5_char.sh): load the wrapped
state dict (``['module']`` for DeepSpeed mp_rank files, ``['state_dict']``
for plain Lightning, else the file itself), strip ``--rm_prefix`` from key
names, and save a bin the family converters / HF loaders can read.
"""

from __future__ import annotations

import argparse


def strip_prefix(state_dict: dict, prefix: str | None) -> dict:
    if not prefix:
        return dict(state_dict)
    n = len(prefix)
    return {(k[n:] if k.startswith(prefix) else k): v
            for k, v in state_dict.items()}


def main(argv=None):
    import torch

    parser = argparse.ArgumentParser("Pretrain Unsupervise.")
    parser.add_argument("--ckpt_path", default=None, type=str)
    parser.add_argument("--bin_path", default=None, type=str)
    parser.add_argument("--rm_prefix", default=None, type=str)
    args = parser.parse_args(argv)

    raw = torch.load(args.ckpt_path, map_location="cpu",
                     weights_only=False)
    state_dict = raw.get("module", raw.get("state_dict", raw)) \
        if isinstance(raw, dict) else raw
    torch.save(strip_prefix(state_dict, args.rm_prefix), args.bin_path)
    print(f"saved {len(state_dict)} tensors -> {args.bin_path}")


if __name__ == "__main__":
    main()
