#!/bin/bash
# Continue pretraining from the last checkpoint (reference:
# fengshen/examples/pretrain_t5/pretrain_mt5_small_continue.sh) — same
# run dir, the resumable sampler restarts from consumed_samples.
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-T5-77M}
ROOT_DIR=${ROOT_DIR:-./workdir/pretrain_t5.pretrain_t5}

python -m fengshen_tpu.examples.pretrain_t5.pretrain_t5 \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-32} \
    --max_steps ${MAX_STEPS:-200000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --max_seq_length 512 --noise_density 0.15
