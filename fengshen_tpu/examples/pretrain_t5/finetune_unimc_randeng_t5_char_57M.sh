#!/bin/bash
# hparams carried from reference: fengshen/examples/pretrain_t5/finetune_unimc_randeng_t5_char_57M.sh
# UniMC-format multiple-choice finetune of the char-level Randeng-T5 57M
set -euo pipefail
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-T5-Char-57M-Chinese}
TRAIN_DATA_DIR=${TRAIN_DATA_DIR:-./data/unimc}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
python -m fengshen_tpu.examples.pretrain_t5.finetune_t5 \
    --pretrained_model_path $MODEL_PATH \
    --tokenizer_type bert_tokenizer \
    --train_data_path $TRAIN_DATA_DIR/train.json \
    --valid_data_path $TRAIN_DATA_DIR/dev.json \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt --load_ckpt_path $ROOT_DIR/ckpt \
    --monitor train_loss --mode min --save_top_k 3 --save_last \
    --every_n_train_steps 100000 \
    --train_batchsize 8 --val_batchsize 8 \
    --max_seq_length 512 \
    --learning_rate 1e-4 --weight_decay 1e-2 --warmup_ratio 0.01 \
    --max_epochs 1 \
    --precision bf16
