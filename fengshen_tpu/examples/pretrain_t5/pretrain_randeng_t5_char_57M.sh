#!/bin/bash
# Randeng-T5 57M span-corruption pretrain
# hparams carried from reference: fengshen/examples/pretrain_t5/pretrain_randeng_t5_char_57M.sh
# TPU: single host by default; scale via the mesh flags
# (--tensor_model_parallel_size / --fsdp_parallel_size) and
# launchers/slurm_multihost.sh or launchers/gke_tpu_job.yaml.
set -euo pipefail

MODEL_PATH=${MODEL_PATH:-./randeng_t5_char_57M}
DATA_DIR=${DATA_DIR:-./data/wudao_180g}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR

# model config for this scale point (written once into the workdir)
if [ ! -f $MODEL_PATH/config.json ]; then
  mkdir -p $MODEL_PATH
  cat > $MODEL_PATH/config.json << EOF
{"vocab_size": 32596, "d_model": 512, "d_ff": 1024,
 "num_layers": 8, "num_decoder_layers": 8,
 "num_heads": 6, "dropout_rate": 0.1, "model_type": "t5"}
EOF
fi

python -m fengshen_tpu.examples.pretrain_t5.pretrain_t5 \
    --tokenizer_type bert_tokenizer \
    --model_path $MODEL_PATH \
    --train_file $DATA_DIR/train.json \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize 64 \
    --max_seq_length 512 \
    --learning_rate 1e-4 \
    --min_learning_rate 1e-5 \
    --warmup_steps 10000 \
    --max_steps 100000 \
    --every_n_train_steps 5000 \
    --tensor_model_parallel_size 1 \
    --fsdp_parallel_size 1 \
    --precision bf16 \
    --seed 1234
