"""Randeng-T5/mT5 span-corruption pretraining.

Port of the reference workload
(reference: fengshen/examples/pretrain_t5/pretrain_t5.py:17-175): mT5
continued pretraining over an unsupervised corpus with T5 span corruption,
including the vocab-trim path (`--keep_tokens_path`) that shrinks an mT5
checkpoint to a Chinese+English vocabulary by index-selecting the embedding
and lm_head rows (reference: pretrain_t5.py:29-49). Run:

    python -m fengshen_tpu.examples.pretrain_t5.pretrain_t5 \
        --train_file corpus.json --model_path <mt5-dir> --max_steps 10000 ...
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.data.t5_dataloader import T5SpanCorruptionCollator
from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


def trim_vocab(params: dict, keep_tokens: list[int]) -> dict:
    """Index-select embedding/lm_head rows to a reduced vocabulary
    (reference: pretrain_t5.py:38-49 torch.index_select on
    encoder/decoder/shared/lm_head weights)."""
    idx = np.asarray(keep_tokens, np.int32)
    out = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    inner = out["model"] if "model" in out else out
    shared = np.asarray(inner["shared"]["embedding"])[idx]
    inner["shared"]["embedding"] = jnp.asarray(shared)
    if "lm_head" in out:
        head = np.asarray(out["lm_head"]["kernel"])[:, idx]
        out["lm_head"]["kernel"] = jnp.asarray(head)
    return out


class T5PretrainModule(TrainModule):
    """Span-corruption seq2seq loss (reference: pretrain_t5.py:82-104)."""

    def __init__(self, args, model=None, config: Optional[T5Config] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = T5Config.from_pretrained(args.model_path)
        self.config = config
        self.model = model or T5ForConditionalGeneration(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("T5 pretrain")
        parser.add_argument("--keep_tokens_path", default=None, type=str)
        parser.add_argument(
            "--new_vocab_path", default=None, type=str,
            help="tokenizer matching keep_tokens order (reference: "
                 "pretrain_t5.py:29-49 continues from mT5 with a reduced "
                 "zh/en sentencepiece model)")
        parser.add_argument("--max_seq_length", type=int, default=512)
        parser.add_argument(
            "--do_eval_only", action="store_true",
            help="restore the checkpoint and run one validation sweep "
                 "only (reference: pretrain_mt5_small_predict.sh)")
        parser.add_argument("--noise_density", type=float, default=0.15)
        parser.add_argument("--mean_noise_span_length", type=float,
                            default=3.0)
        parser.add_argument(
            "--tokenizer_type", default="t5_tokenizer", type=str,
            choices=["t5_tokenizer", "bert_tokenizer"],
            help="bert_tokenizer = char-level Randeng vocab behind the "
                 "T5Tokenizer wrapper (reference: pretrain_t5.py:27 + "
                 "models/megatron_t5/tokenization_megatron_t5.py)")
        return parent_parser

    def init_params(self, rng):
        keep_path = getattr(self.args, "keep_tokens_path", None)
        model_path = getattr(self.args, "model_path", None)
        if not keep_path:
            ids = jnp.zeros((1, 8), jnp.int32)
            return self.model.init(rng, ids, ids)["params"]
        # the vocab trim only makes sense on PRETRAINED weights (the
        # reference index-selects the loaded mT5 state dict,
        # pretrain_t5.py:38-49) with the NEW tokenizer whose ids match
        # keep_tokens order (--new_vocab_path). Require the checkpoint.
        import os
        ckpt = os.path.join(model_path or "", "pytorch_model.bin")
        if not os.path.exists(ckpt):
            raise ValueError(
                "--keep_tokens_path requires a pretrained torch "
                f"checkpoint at {ckpt} (trimming random weights would "
                "discard nothing and misalign the new vocabulary)")
        import torch

        from fengshen_tpu.models.t5.convert import torch_to_params
        params = torch_to_params(
            torch.load(ckpt, map_location="cpu"), self.config)
        with open(keep_path) as f:
            keep = json.load(f)
        return trim_vocab(params, keep)

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            batch["decoder_input_ids"],
            attention_mask=batch.get("attention_mask"),
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits,
                                                      batch["labels"])
        valid = batch["labels"] != -100
        acc = ((logits.argmax(-1) == batch["labels"]) * valid).sum() / \
            jnp.maximum(valid.sum(), 1)
        return loss, {"acc": acc, "n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser("Pretrain Unsupervise.")
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = T5PretrainModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    if args.tokenizer_type == "bert_tokenizer":
        from fengshen_tpu.models.t5 import T5Tokenizer
        tokenizer = T5Tokenizer.from_pretrained(
            args.new_vocab_path or args.model_path)
    else:
        tokenizer = AutoTokenizer.from_pretrained(
            args.new_vocab_path or args.model_path)
    collator = T5SpanCorruptionCollator(
        tokenizer, max_seq_length=args.max_seq_length,
        noise_density=args.noise_density,
        mean_noise_span_length=args.mean_noise_span_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = T5PretrainModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    if args.do_eval_only:
        trainer.validate(module, datamodule)
    else:
        trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
