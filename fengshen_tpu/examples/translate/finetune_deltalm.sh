#!/bin/bash
# Launcher for translate.finetune_deltalm (reference pattern: fengshen/examples/translate/finetune_deltalm.sh)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Randeng-Deltalm-362M-Zh-En}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.translate.finetune_deltalm \
    --model_path $MODEL_PATH \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-1e-4} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --train_file $TRAIN_FILE --max_enc_length 256 --max_dec_length 256
