"""DeltaLM machine-translation finetune (zh↔en).

Port of the reference workload
(reference: fengshen/examples/translate/finetune_deltalm.py:85-320):
{src, tgt} pairs (optionally reversed via --reverse_src_tgt) trained as
seq2seq CE on DeltaLMForConditionalGeneration.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.examples.summary.seq2seq_summary import Seq2SeqCollator
from fengshen_tpu.models.deltalm import (DeltaLMConfig,
                                         DeltaLMForConditionalGeneration)
from fengshen_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class TranslationCollator(Seq2SeqCollator):
    """{src, tgt} → seq2seq batch (reference: finetune_deltalm.py:85-123);
    batching inherited from Seq2SeqCollator, only src/tgt selection (and
    the --reverse_src_tgt direction flip) here."""

    reverse_src_tgt: bool = False

    def source_text(self, sample: dict) -> str:
        return sample["tgt"] if self.reverse_src_tgt else sample["src"]

    def target_text(self, sample: dict) -> str:
        return sample["src"] if self.reverse_src_tgt else sample["tgt"]


class DeltaLMTranslationModule(TrainModule):
    """reference: finetune_deltalm.py FinetuneTranslation."""

    def __init__(self, args, config: Optional[DeltaLMConfig] = None):
        super().__init__(args)
        if config is None and getattr(args, "model_path", None):
            config = DeltaLMConfig.from_pretrained(args.model_path)
        self.config = config
        self.model = DeltaLMForConditionalGeneration(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("deltalm translate")
        parser.add_argument("--max_enc_length", type=int, default=256)
        parser.add_argument("--max_dec_length", type=int, default=256)
        parser.add_argument("--reverse_src_tgt", action="store_true",
                            default=False)
        parser.add_argument("--label_smooth", type=float, default=0.1)
        return parent_parser

    def init_params(self, rng):
        ids = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, ids, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            batch["decoder_input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, n_tokens = vocab_parallel_cross_entropy(logits,
                                                      batch["labels"])
        smooth = getattr(self.args, "label_smooth", 0.0)
        if smooth:
            # uniform label smoothing (reference uses LabelSmoothingLoss,
            # finetune_deltalm.py:30-60)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            valid = (batch["labels"] != -100)[..., None]
            uniform = -(logp * valid).mean(-1).sum() / \
                jnp.maximum(valid.sum(), 1)
            loss = (1 - smooth) * loss + smooth * uniform
        return loss, {"n_tokens": n_tokens}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = DeltaLMTranslationModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    module = DeltaLMTranslationModule(args)
    collator = TranslationCollator(
        tokenizer, max_src_length=args.max_enc_length,
        max_tgt_length=args.max_dec_length,
        decoder_start_token_id=module.config.decoder_start_token_id,
        reverse_src_tgt=args.reverse_src_tgt)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
