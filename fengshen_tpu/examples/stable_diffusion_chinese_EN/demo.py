"""Taiyi Stable Diffusion bilingual (zh/EN) txt2img demo — the _EN variant
of stable_diffusion_chinese (reference:
fengshen/examples/stable_diffusion_chinese_EN/), identical pipeline with a
bilingual text-encoder checkpoint."""

from fengshen_tpu.examples.stable_diffusion_chinese.demo import main

if __name__ == "__main__":
    main()
