"""Taiyi Stable Diffusion bilingual (zh/EN) txt2img demo.

The _EN variant of stable_diffusion_chinese (reference:
fengshen/examples/stable_diffusion_chinese_EN/): the SAME sampling
pipeline driven by the bilingual text-encoder checkpoint
(Taiyi-Stable-Diffusion-1B-Chinese-EN-v0.1), so English prompts work
alongside Chinese ones.
"""

from __future__ import annotations

DEFAULT_BILINGUAL_CHECKPOINT = \
    "IDEA-CCNL/Taiyi-Stable-Diffusion-1B-Chinese-EN-v0.1"


def main(argv=None, **kwargs):
    from fengshen_tpu.examples.stable_diffusion_chinese.demo import (
        main as zh_main)

    argv = list(argv) if argv is not None else []
    if "--model_path" not in argv:
        argv = ["--model_path", DEFAULT_BILINGUAL_CHECKPOINT] + argv
    if "--prompt" not in argv:
        # the reference _EN demo's headline English prompt
        argv = argv + ["--prompt", "a colorful painting of a castle, "
                                   "fantasy, detailed"]
    return zh_main(argv, **kwargs)


if __name__ == "__main__":
    main()
