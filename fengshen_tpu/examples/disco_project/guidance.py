"""Disco-diffusion guidance machinery, TPU-native.

Faithful port of the reference's CLIP-guidance core (reference:
fengshen/examples/disco_project/disco.py — `MakeCutoutsDango` :279-353,
`spherical_dist_loss`/`tv_loss`/`range_loss` :354-370, `cond_fn`
:600-650) re-expressed in jnp over NHWC images:

- cutouts: overview crops (padded-square resize, with grayscale and
  horizontal-flip variants) + random inner crops — dynamic crop+resize is
  one `jax.image.scale_and_translate` with a STATIC output shape, so the
  whole cutout batch jits; the reference's torch augs reduce to the
  jit-compatible subset (gaussian noise + random hflip + grayscale
  probability; affine/color-jitter are omitted).
- losses: spherical CLIP distance, L2 total variation, out-of-range and
  saturation penalties.
- classifier guidance on the LATENT diffusion of the SD towers: the
  reference guides a pixel-space model via `cond_fn`; here the gradient
  flows through the VAE decode of the pred-x0 interpolated latent and
  bends ε (`eps' = eps − sqrt(1−ᾱ)·∇`), with the reference's
  magnitude clamp (`clamp_grad` :648-650).

The reference's per-timestep cutout schedules ([12]*400+[4]*600 etc.)
index by 1000−t; counts must be static under jit, so the sampler runs a
Python loop and caches one compiled step per (overview, innercut) phase.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from fengshen_tpu.models.stable_diffusion.autoencoder_kl import (
    SCALING_FACTOR)
from fengshen_tpu.models.stable_diffusion.scheduler import DDPMScheduler


# -- losses (reference: disco.py:354-370) ---------------------------------

def spherical_dist_loss(x, y):
    x = x / jnp.linalg.norm(x, axis=-1, keepdims=True)
    y = y / jnp.linalg.norm(y, axis=-1, keepdims=True)
    half = jnp.linalg.norm(x - y, axis=-1) / 2.0
    return 2.0 * jnp.arcsin(jnp.clip(half, 0.0, 1.0)) ** 2


def tv_loss(img):
    """L2 total variation over NHWC (replicate-padded like the torch
    original)."""
    img = jnp.pad(img, ((0, 0), (0, 1), (0, 1), (0, 0)), mode="edge")
    x_diff = img[:, :-1, 1:] - img[:, :-1, :-1]
    y_diff = img[:, 1:, :-1] - img[:, :-1, :-1]
    return (x_diff ** 2 + y_diff ** 2).mean(axis=(1, 2, 3))


def range_loss(img):
    return ((img - jnp.clip(img, -1.0, 1.0)) ** 2).mean(axis=(1, 2, 3))


def sat_loss(img):
    return jnp.abs(img - jnp.clip(img, -1.0, 1.0)).mean()


def _grayscale(img):
    w = jnp.asarray([0.2989, 0.587, 0.114], img.dtype)
    g = (img * w).sum(-1, keepdims=True)
    return jnp.broadcast_to(g, img.shape)


#: CLIP preprocessing stats (reference disco.py `normalize`; same
#: constants as data/clip_dataloader/image_text.py CLIPCollator)
CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_STD = (0.26862954, 0.26130258, 0.27577711)


def clip_normalize(img):
    """[0,1] pixels → CLIP-normalized (applied AFTER the cutout augs,
    like the reference's `normalize(cuts(...))`, disco.py:628)."""
    mean = jnp.asarray(CLIP_MEAN, img.dtype)
    std = jnp.asarray(CLIP_STD, img.dtype)
    return (img - mean) / std


# -- cutouts (reference: MakeCutoutsDango, disco.py:279-353) --------------

def make_cutouts(rng, img, cut_size: int, overview: int = 4,
                 innercut: int = 0, ic_size_pow: float = 0.5,
                 ic_grey_p: float = 0.2, skip_augs: bool = False):
    """img [B,H,W,C] in [0,1] → cutouts [(overview+innercut)·B,
    cut_size, cut_size, C]. Counts are STATIC; offsets/sizes are traced."""
    b, h, w, c = img.shape
    cuts = []

    base = jax.image.resize(img, (b, cut_size, cut_size, c), "bilinear")
    variants = [base, _grayscale(base), base[:, :, ::-1],
                _grayscale(base)[:, :, ::-1]]
    for i in range(min(max(overview, 0), 4)):
        cuts.append(variants[i])
    for _ in range(max(overview - 4, 0)):
        cuts.append(base)

    max_size = min(h, w)
    min_size = min(h, w, cut_size)
    for i in range(innercut):
        rng, r_size, r_x, r_y = jax.random.split(rng, 4)
        size = (jax.random.uniform(r_size) ** ic_size_pow *
                (max_size - min_size) + min_size)
        off_x = jax.random.uniform(r_x) * (w - size)
        off_y = jax.random.uniform(r_y) * (h - size)
        # crop [off, off+size) then resize → one scale_and_translate
        scale = cut_size / size
        cut = jax.image.scale_and_translate(
            img, (b, cut_size, cut_size, c), (1, 2),
            jnp.stack([scale, scale]),
            jnp.stack([-off_y * scale, -off_x * scale]),
            method="bilinear")
        # `<=` reproduces the reference exactly (MakeCutoutsDango,
        # disco.py:341): its off-by-one grayscales the FIRST inner cut
        # even at grey_p=0 — kept for output parity with the original
        if i <= int(ic_grey_p * innercut) and innercut > 0:
            cut = _grayscale(cut)
        cuts.append(cut)

    out = jnp.concatenate(cuts, axis=0)
    if not skip_augs:
        rng, r_noise, r_flip = jax.random.split(rng, 3)
        out = out + jax.random.normal(r_noise, out.shape) * 0.01
        flip = jax.random.bernoulli(r_flip, 0.5, (out.shape[0], 1, 1, 1))
        out = jnp.where(flip, out[:, :, ::-1], out)
    return out


# -- schedules (reference defaults: disco.py:75-90) -----------------------

@dataclasses.dataclass
class DiscoConfig:
    clip_guidance_scale: float = 5000.0
    tv_scale: float = 0.0
    range_scale: float = 150.0
    sat_scale: float = 0.0
    clamp_grad: bool = True
    clamp_max: float = 0.05
    cutn_batches: int = 1
    # two-phase cutout schedule, switching at t=600 (i.e. 1000-t >= 400)
    cut_overview_early: int = 12
    cut_overview_late: int = 4
    cut_innercut_early: int = 4
    cut_innercut_late: int = 12
    ic_size_pow: float = 1.0
    ic_grey_p_early: float = 0.2
    ic_grey_p_late: float = 0.0

    def phase(self, t: int, total: int = 1000):
        early = (total - int(t)) < 400
        if early:
            return (self.cut_overview_early, self.cut_innercut_early,
                    self.ic_grey_p_early)
        return (self.cut_overview_late, self.cut_innercut_late,
                self.ic_grey_p_late)


# -- CLIP-guided sampling over the SD towers ------------------------------

def clip_guided_sample(sd_model, sd_params, clip_model, clip_params,
                       input_ids, clip_text_ids,
                       image_size: int = 64, num_steps: int = 20,
                       config: Optional[DiscoConfig] = None,
                       scheduler: Optional[DDPMScheduler] = None,
                       rng=None):
    """The disco loop on the latent SD towers: at every denoise step the
    ε-prediction is bent by the gradient of the CLIP-cutout similarity
    (+ tv/range/sat penalties) taken through the VAE decode of the
    pred-x0 interpolated latent (reference cond_fn: disco.py:600-650)."""
    import numpy as np

    config = config or DiscoConfig()
    scheduler = scheduler or DDPMScheduler()
    rng = jax.random.PRNGKey(0) if rng is None else rng
    batch = input_ids.shape[0]
    latent_shape = (batch,) + sd_model.vae_config.latent_shape(image_size)

    text_states = sd_model.apply({"params": sd_params}, input_ids,
                                 method=type(sd_model).encode_text)
    clip_text = clip_model.apply({"params": clip_params},
                                 input_ids=clip_text_ids,
                                 pixel_values=None)[0]
    clip_size = clip_model.vision_config.image_size

    def decode(latents):
        return sd_model.apply({"params": sd_params},
                              latents / SCALING_FACTOR,
                              method=lambda m, z: m.vae.decode(z))

    def denoise(latents, tb):
        return sd_model.apply({"params": sd_params}, latents, tb,
                              text_states,
                              method=type(sd_model).denoise)

    alphas = scheduler.alphas_cumprod

    def make_step(overview, innercut, grey_p):
        def guidance_loss(latents, x0_lat, fac, g_rng):
            # the reference interpolates pred_xstart toward x by
            # sqrt(1-ᾱ) before the cutouts (cond_fn: disco.py:608-610)
            lat_in = x0_lat * fac + latents * (1.0 - fac)
            x_in = decode(lat_in)  # [-1, 1]-ish pixels
            loss = 0.0
            if config.clip_guidance_scale:
                # cutn_batches independent cutout draws, gradients
                # averaged (reference cond_fn: disco.py:613-633)
                clip_loss = 0.0
                for cb in range(config.cutn_batches):
                    cuts = make_cutouts(
                        jax.random.fold_in(g_rng, cb),
                        x_in / 2.0 + 0.5, clip_size,
                        overview=overview, innercut=innercut,
                        ic_size_pow=config.ic_size_pow,
                        ic_grey_p=grey_p)
                    _, img_emb, _ = clip_model.apply(
                        {"params": clip_params}, input_ids=None,
                        pixel_values=clip_normalize(cuts))
                    n_cuts = overview + innercut
                    dists = spherical_dist_loss(
                        img_emb.reshape(n_cuts, batch, -1),
                        clip_text[None])
                    clip_loss = clip_loss + dists.sum(0).mean()
                loss = loss + config.clip_guidance_scale * \
                    clip_loss / config.cutn_batches
            if config.tv_scale:
                loss = loss + config.tv_scale * tv_loss(x_in).sum()
            if config.range_scale:
                loss = loss + config.range_scale * \
                    range_loss(decode(x0_lat)).sum()
            if config.sat_scale:
                loss = loss + config.sat_scale * sat_loss(x_in)
            return loss

        def step(latents, t, t_prev, g_rng):
            tb = jnp.full((batch,), t, jnp.int32)
            eps = denoise(latents, tb)
            a_t = alphas[t]
            x0_lat = (latents - jnp.sqrt(1 - a_t) * eps) / jnp.sqrt(a_t)
            fac = jnp.sqrt(1 - a_t)
            grad = jax.grad(guidance_loss)(latents, x0_lat, fac, g_rng)
            if config.clamp_grad:
                mag = jnp.sqrt(jnp.mean(grad ** 2))
                grad = grad * jnp.minimum(mag, config.clamp_max) / \
                    jnp.maximum(mag, 1e-12)
            # classifier guidance bends ε: eps' = eps − sqrt(1−ᾱ)·(−∇)
            eps = eps + jnp.sqrt(1 - a_t) * grad
            return scheduler.step(eps, t, latents, prev_timestep=t_prev)

        return jax.jit(step)

    steps_cache: dict = {}
    T = scheduler.num_train_timesteps
    timesteps = np.linspace(T - 1, 0, num_steps).astype(np.int32)
    prev_timesteps = np.concatenate([timesteps[1:], [-1]]).astype(np.int32)

    rng, init_rng = jax.random.split(rng)
    latents = jax.random.normal(init_rng, latent_shape)
    for t, t_prev in zip(timesteps, prev_timesteps):
        phase = config.phase(int(t), T)
        if phase not in steps_cache:
            steps_cache[phase] = make_step(*phase)
        rng, g_rng = jax.random.split(rng)
        latents = steps_cache[phase](latents, jnp.int32(t),
                                     jnp.int32(t_prev), g_rng)

    pixels = decode(latents)
    return jnp.clip(pixels / 2.0 + 0.5, 0.0, 1.0)
