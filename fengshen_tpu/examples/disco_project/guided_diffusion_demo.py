"""CLIP-guided diffusion (disco) over the SD towers.

The reference project (reference: fengshen/examples/disco_project/
disco.py — disco-diffusion with the Taiyi Chinese CLIP) guides every
denoise step by the gradient of the CLIP similarity between augmented
cutouts of the decoded image and the text prompt, plus TV/range/sat
regularizers. The full machinery lives in `guidance.py` (cutouts,
spherical distance, losses, ε-bending with magnitude clamp); this
driver wires it to the Taiyi SD towers — the faithful SD-1.x
architecture when `--sd_pipeline_path` points at a released diffusers
dir, or compact random-init towers for the demo path.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.examples.disco_project.guidance import (DiscoConfig,
                                                          clip_guided_sample)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--prompt", type=str, default="一幅山水画")
    parser.add_argument("--image_size", type=int, default=32)
    parser.add_argument("--num_steps", type=int, default=4)
    parser.add_argument("--clip_guidance_scale", type=float, default=500.0)
    parser.add_argument("--tv_scale", type=float, default=0.0)
    parser.add_argument("--range_scale", type=float, default=150.0)
    parser.add_argument("--sat_scale", type=float, default=0.0)
    parser.add_argument("--sd_pipeline_path", type=str, default=None,
                        help="released diffusers pipeline dir → faithful "
                             "SD-1.x towers with imported weights "
                             "(requires --model_path for the matching "
                             "Chinese text encoder)")
    parser.add_argument("--model_path", type=str, default=None,
                        help="Taiyi text-encoder dir (BertConfig); "
                             "required with --sd_pipeline_path so the "
                             "cross-attention dims match")
    parser.add_argument("--faithful_towers", action="store_true",
                        default=False)
    parser.add_argument("--output", type=str, default=None,
                        help="save the first sample as a PNG")
    args = parser.parse_args(argv)

    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.clip import CLIPVisionConfig, TaiyiCLIPModel
    from fengshen_tpu.models.stable_diffusion.modeling_taiyi_sd import (
        TaiyiStableDiffusion)

    from fengshen_tpu.models.stable_diffusion.convert import resolve_towers

    if args.sd_pipeline_path:
        if not args.model_path:
            raise SystemExit(
                "--sd_pipeline_path needs --model_path: the released "
                "UNet's cross-attention expects the matching Chinese "
                "text encoder (hidden 768), not the demo toy config")
        text_cfg = BertConfig.from_pretrained(args.model_path)
    else:
        text_cfg = BertConfig.small_test_config()
    unet_cfg, vae_cfg, pipeline_params = resolve_towers(
        args.sd_pipeline_path, faithful=args.faithful_towers,
        small_test=True)
    sd = TaiyiStableDiffusion(text_cfg, vae_cfg, unet_cfg)

    vis_cfg = CLIPVisionConfig.small_test_config(
        image_size=args.image_size)
    clip = TaiyiCLIPModel(text_cfg, vis_cfg)

    from fengshen_tpu.examples.demo_utils import toy_encode
    ids = jnp.asarray([toy_encode(args.prompt)], jnp.int32)
    size = args.image_size
    from fengshen_tpu.models.stable_diffusion.sampling import (
        init_sampling_params)
    sd_params = init_sampling_params(sd, jax.random.PRNGKey(0), size)
    if pipeline_params is not None:
        sd_params = dict(sd_params)
        sd_params.update(pipeline_params)
        # the released text-encoder weights too, when --model_path holds
        # a torch checkpoint — a random text tower would make the UNet's
        # conditioning noise
        try:
            from fengshen_tpu.models.stable_diffusion.convert import (
                text_encoder_to_params)
            from fengshen_tpu.utils.convert_common import (
                load_torch_checkpoint)
            state = load_torch_checkpoint(args.model_path)
            sd_params["text_encoder"] = text_encoder_to_params(
                state, text_cfg)
        except FileNotFoundError:
            print("WARNING: no torch checkpoint under --model_path; the "
                  "text encoder stays randomly initialized and the "
                  "prompt will not steer the UNet")
    clip_params = clip.init(
        jax.random.PRNGKey(1), ids,
        jnp.zeros((1, vis_cfg.image_size, vis_cfg.image_size, 3)))["params"]

    config = DiscoConfig(
        clip_guidance_scale=args.clip_guidance_scale,
        tv_scale=args.tv_scale, range_scale=args.range_scale,
        sat_scale=args.sat_scale,
        # demo shapes are tiny; keep the cutout batches small
        cut_overview_early=4, cut_overview_late=2,
        cut_innercut_early=1, cut_innercut_late=2)
    images = clip_guided_sample(sd, sd_params, clip, clip_params, ids,
                                ids, image_size=size,
                                num_steps=args.num_steps, config=config)
    arr = np.asarray(images)
    print("sampled:", arr.shape)
    if args.output:
        from PIL import Image
        Image.fromarray(
            (arr[0] * 255).astype(np.uint8)).save(args.output)
        print("saved:", args.output)
    return arr


if __name__ == "__main__":
    main()
