"""CLIP-guided diffusion (disco-style) demo.

Port of the reference project (reference: fengshen/examples/disco_project/
— disco-diffusion with the Taiyi Chinese CLIP): at each DDPM step the
latent is nudged by the gradient of the CLIP similarity between the
decoded image and the text prompt.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np


def clip_guided_sample(sd_model, sd_params, clip_model, clip_params,
                       input_ids, clip_text_ids, image_size: int = 64,
                       num_steps: int = 20, guidance_strength: float = 0.5,
                       rng=None):
    """DDPM sampling with CLIP-similarity gradient guidance: the shared
    text_to_image loop with a per-step latent-guidance hook (the
    disco-diffusion core)."""
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import (
        SCALING_FACTOR)
    from fengshen_tpu.models.stable_diffusion.sampling import text_to_image

    batch = input_ids.shape[0]
    clip_text = clip_model.apply(
        {"params": clip_params}, input_ids=clip_text_ids,
        pixel_values=None)[0]

    def clip_score(latents):
        pixels = sd_model.apply(
            {"params": sd_params}, latents / SCALING_FACTOR,
            method=lambda m, z: m.vae.decode(z))
        size = clip_model.vision_config.image_size
        pixels = jax.image.resize(
            pixels, (batch, size, size, pixels.shape[-1]), "bilinear")
        _, img_emb, _ = clip_model.apply({"params": clip_params},
                                         input_ids=None,
                                         pixel_values=pixels)
        return (clip_text * img_emb).sum(-1).mean()

    grad_fn = jax.grad(clip_score)

    def guide(latents):
        return latents + guidance_strength * grad_fn(latents)

    return text_to_image(sd_model, sd_params, input_ids,
                         image_size=image_size, num_steps=num_steps,
                         guidance_scale=0.0, rng=rng,
                         latent_guidance_fn=guide)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--prompt", type=str, default="一幅山水画")
    parser.add_argument("--image_size", type=int, default=32)
    parser.add_argument("--num_steps", type=int, default=4)
    args = parser.parse_args(argv)

    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.clip import CLIPVisionConfig, TaiyiCLIPModel
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import VAEConfig
    from fengshen_tpu.models.stable_diffusion.modeling_taiyi_sd import (
        TaiyiStableDiffusion)
    from fengshen_tpu.models.stable_diffusion.unet import UNetConfig

    text_cfg = BertConfig.small_test_config()
    sd = TaiyiStableDiffusion(text_cfg, VAEConfig.small_test_config(),
                              UNetConfig.small_test_config())
    vis_cfg = CLIPVisionConfig.small_test_config(
        image_size=args.image_size)
    clip = TaiyiCLIPModel(text_cfg, vis_cfg)

    from fengshen_tpu.examples.demo_utils import toy_encode
    ids = jnp.asarray([toy_encode(args.prompt)], jnp.int32)
    size = args.image_size
    from fengshen_tpu.models.stable_diffusion.sampling import (
        init_sampling_params)
    sd_params = init_sampling_params(sd, jax.random.PRNGKey(0), size)
    clip_params = clip.init(
        jax.random.PRNGKey(1), ids,
        jnp.zeros((1, vis_cfg.image_size, vis_cfg.image_size, 3)))["params"]

    images = clip_guided_sample(sd, sd_params, clip, clip_params, ids, ids,
                                image_size=size, num_steps=args.num_steps)
    print("sampled:", images.shape)
    return np.asarray(images)


if __name__ == "__main__":
    main()
