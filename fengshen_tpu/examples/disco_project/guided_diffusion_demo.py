"""CLIP-guided diffusion (disco-style) demo.

Port of the reference project (reference: fengshen/examples/disco_project/
— disco-diffusion with the Taiyi Chinese CLIP): at each DDPM step the
latent is nudged by the gradient of the CLIP similarity between the
decoded image and the text prompt.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np


def clip_guided_sample(sd_model, sd_params, clip_model, clip_params,
                       input_ids, clip_text_ids, image_size: int = 64,
                       num_steps: int = 20, guidance_strength: float = 0.5,
                       rng=None):
    """DDPM sampling with CLIP-similarity gradient guidance
    (the disco-diffusion core loop)."""
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import (
        SCALING_FACTOR)
    from fengshen_tpu.models.stable_diffusion.scheduler import DDPMScheduler

    scheduler = DDPMScheduler()
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    batch = input_ids.shape[0]
    latent_shape = (batch,) + sd_model.vae_config.latent_shape(image_size)
    text = sd_model.apply({"params": sd_params}, input_ids,
                          method=type(sd_model).encode_text)
    clip_text = clip_model.apply(
        {"params": clip_params}, input_ids=clip_text_ids,
        pixel_values=None)[0]

    def clip_score(latents):
        pixels = sd_model.apply(
            {"params": sd_params}, latents / SCALING_FACTOR,
            method=lambda m, z: m.vae.decode(z))
        size = clip_model.vision_config.image_size
        pixels = jax.image.resize(
            pixels, (batch, size, size, pixels.shape[-1]), "bilinear")
        _, img_emb, _ = clip_model.apply({"params": clip_params},
                                         input_ids=None,
                                         pixel_values=pixels)
        return (clip_text * img_emb).sum(-1).mean()

    grad_fn = jax.grad(clip_score)
    latents = jax.random.normal(rng, latent_shape)
    T = scheduler.num_train_timesteps
    schedule = np.linspace(T - 1, 0, num_steps).astype(np.int32)
    prevs = np.append(schedule[1:], -1)
    for t, t_prev in zip(schedule, prevs):
        tb = jnp.full((batch,), int(t), jnp.int32)
        eps = sd_model.apply({"params": sd_params}, latents, tb, text,
                             method=type(sd_model).denoise)
        latents = scheduler.step(eps, int(t), latents,
                                 prev_timestep=int(t_prev))
        latents = latents + guidance_strength * grad_fn(latents)
    pixels = sd_model.apply({"params": sd_params},
                            latents / SCALING_FACTOR,
                            method=lambda m, z: m.vae.decode(z))
    return jnp.clip(pixels / 2.0 + 0.5, 0.0, 1.0)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--prompt", type=str, default="一幅山水画")
    parser.add_argument("--image_size", type=int, default=32)
    parser.add_argument("--num_steps", type=int, default=4)
    args = parser.parse_args(argv)

    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.clip import CLIPVisionConfig, TaiyiCLIPModel
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import VAEConfig
    from fengshen_tpu.models.stable_diffusion.modeling_taiyi_sd import (
        TaiyiStableDiffusion)
    from fengshen_tpu.models.stable_diffusion.unet import UNetConfig

    text_cfg = BertConfig.small_test_config()
    sd = TaiyiStableDiffusion(text_cfg, VAEConfig.small_test_config(),
                              UNetConfig.small_test_config())
    vis_cfg = CLIPVisionConfig.small_test_config(
        image_size=args.image_size)
    clip = TaiyiCLIPModel(text_cfg, vis_cfg)

    from fengshen_tpu.examples.demo_utils import toy_encode
    ids = jnp.asarray([toy_encode(args.prompt)], jnp.int32)
    size = args.image_size
    from fengshen_tpu.models.stable_diffusion.sampling import (
        init_sampling_params)
    sd_params = init_sampling_params(sd, jax.random.PRNGKey(0), size)
    clip_params = clip.init(
        jax.random.PRNGKey(1), ids,
        jnp.zeros((1, vis_cfg.image_size, vis_cfg.image_size, 3)))["params"]

    images = clip_guided_sample(sd, sd_params, clip, clip_params, ids, ids,
                                image_size=size, num_steps=args.num_steps)
    print("sampled:", images.shape)
    return np.asarray(images)


if __name__ == "__main__":
    main()
