"""Ziya-LLaMA inference demo.

Port of reference: fengshen/examples/ziya_inference/ (HF generation demo;
the reference also ships 8-bit/llama.cpp variants — quantized serving is a
round-2 item, see NOTES.md). Loads an HF llama checkpoint, applies the
"<human>:/<bot>:" chat format, and generates with sampling.

    python -m fengshen_tpu.examples.ziya_inference.generate_ziya \
        --model_path <hf-llama-dir> --query "帮我写一首诗" --top_p 0.85
"""

from __future__ import annotations

import argparse


def main(argv=None):
    import jax
    import jax.numpy as jnp
    from transformers import AutoTokenizer

    from fengshen_tpu.models.llama import LlamaForCausalLM
    from fengshen_tpu.models.llama.convert import load_hf_pretrained
    from fengshen_tpu.utils.generate import (generate,
                                             speculative_generate)

    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", required=True, type=str)
    parser.add_argument("--query", required=True, type=str)
    parser.add_argument("--max_new_tokens", default=128, type=int)
    parser.add_argument("--do_sample", action="store_true", default=True)
    parser.add_argument("--greedy", action="store_true", default=False,
                        help="force greedy decode (--do_sample defaults "
                             "on for reference parity and store_true "
                             "can't turn it off)")
    parser.add_argument("--temperature", default=0.8, type=float)
    parser.add_argument("--top_k", default=0, type=int)
    parser.add_argument("--top_p", default=0.85, type=float)
    parser.add_argument("--seed", default=42, type=int)
    parser.add_argument(
        "--draft_model_path", default=None, type=str,
        help="HF llama dir of a SMALL same-tokenizer draft model: "
             "switches to speculative decoding — greedy is token-exact "
             "vs plain greedy; with --do_sample the rejection scheme "
             "makes every token distributed exactly as plain sampling. "
             "The target runs once per 1..gamma+1 tokens")
    parser.add_argument("--gamma", default=4, type=int,
                        help="draft tokens proposed per verify forward")
    parser.add_argument(
        "--self_draft_layers", default=0, type=int,
        help="speculative decoding WITHOUT a second checkpoint: use the "
             "target's own first N layers (+ shared embeddings/norm/"
             "head) as the draft. Mutually exclusive with "
             "--draft_model_path")
    parser.add_argument(
        "--prompt_lookup", default=0, type=int,
        help="DRAFT-FREE speculation: propose the continuation of the "
             "latest earlier occurrence of the current N-gram suffix "
             "and verify with one target forward (token-exact greedy; "
             "big wins on extractive/repetitive outputs). Mutually "
             "exclusive with the draft flags")
    args = parser.parse_args(argv)
    if args.greedy:
        args.do_sample = False
    if sum(bool(x) for x in (args.draft_model_path,
                             args.self_draft_layers,
                             args.prompt_lookup)) > 1:
        raise SystemExit("--draft_model_path, --self_draft_layers and "
                         "--prompt_lookup are mutually exclusive")

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    config, params = load_hf_pretrained(args.model_path)
    model = LlamaForCausalLM(config)

    prompt = f"<human>:{args.query.strip()}\n<bot>:"
    ids = tokenizer.encode(prompt)
    if args.draft_model_path or args.self_draft_layers:
        if args.self_draft_layers:
            from fengshen_tpu.models.llama import make_self_draft
            d_config, d_params = make_self_draft(
                config, params, args.self_draft_layers)
        else:
            d_config, d_params = load_hf_pretrained(
                args.draft_model_path)
        draft = LlamaForCausalLM(d_config)
        out, stats = speculative_generate(
            model, params, draft, d_params,
            jnp.asarray([ids], jnp.int32),
            max_new_tokens=args.max_new_tokens, gamma=args.gamma,
            do_sample=args.do_sample, temperature=args.temperature,
            top_k=args.top_k, top_p=args.top_p,
            eos_token_id=config.eos_token_id,
            pad_token_id=config.pad_token_id,
            rng=jax.random.PRNGKey(args.seed), return_stats=True)
        print(f"[speculative] rounds={int(stats['rounds'])} "
              f"accepted={int(stats['accepted'])}/"
              f"{int(stats['drafted'])} drafted")
    elif args.prompt_lookup:
        from fengshen_tpu.utils.generate import prompt_lookup_generate
        if args.do_sample:
            print("[prompt-lookup] greedy-only (no draft distribution "
                  "to reject against): ignoring sampling flags")
        out, stats = prompt_lookup_generate(
            model, params, jnp.asarray([ids], jnp.int32),
            max_new_tokens=args.max_new_tokens, gamma=args.gamma,
            ngram=args.prompt_lookup,
            eos_token_id=config.eos_token_id,
            pad_token_id=config.pad_token_id, return_stats=True)
        print(f"[prompt-lookup] rounds={int(stats['rounds'])} "
              f"accepted={int(stats['accepted'])}/"
              f"{int(stats['drafted'])} drafted")
    else:
        out = generate(model, params, jnp.asarray([ids], jnp.int32),
                       max_new_tokens=args.max_new_tokens,
                       do_sample=args.do_sample,
                       temperature=args.temperature,
                       top_k=args.top_k, top_p=args.top_p,
                       eos_token_id=config.eos_token_id,
                       pad_token_id=config.pad_token_id,
                       rng=jax.random.PRNGKey(args.seed))
    text = tokenizer.decode(list(out[0][len(ids):]),
                            skip_special_tokens=True)
    print(text.strip())


if __name__ == "__main__":
    main()
