"""Ziya-LLaMA int8 serving demo.

Port of the reference's quantized serving paths
(reference: fengshen/examples/ziya_inference/ — `load_in_8bit=True` and
the llama.cpp recipe): weights are int8 at rest (half the HBM/checkpoint),
dequantized inside the jitted decode step where XLA fuses the upcast into
each matmul.

    python -m fengshen_tpu.examples.ziya_inference.generate_ziya_int8 \
        --model_path <ziya-dir> --prompt "帮我写一首关于春天的诗"
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.utils.generate import generate
from fengshen_tpu.utils.quantization import (dequantize_params,
                                             quantize_params_int8,
                                             quantized_nbytes)


def quantized_generate(model, qparams, input_ids, attention_mask=None,
                       max_new_tokens: int = 64, **kwargs):
    """generate() over int8 weights: dequant happens inside the jitted
    steps (generate jits the decode loop), so bf16 copies are transient."""

    class _DequantApply:
        """Adapter: model whose apply dequantizes on entry."""

        def __init__(self, model):
            self._model = model

        def init(self, *a, **k):
            return self._model.init(*a, **k)

        def apply(self, variables, *a, **k):
            variables = dict(variables)
            variables["params"] = dequantize_params(variables["params"])
            return self._model.apply(variables, *a, **k)

    return generate(_DequantApply(model), qparams, input_ids,
                    attention_mask=attention_mask,
                    max_new_tokens=max_new_tokens, **kwargs)


def main(argv=None):
    from transformers import AutoTokenizer

    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", type=str, required=True)
    parser.add_argument("--prompt", type=str,
                        default="帮我写一首关于春天的诗")
    parser.add_argument("--max_new_tokens", type=int, default=128)
    parser.add_argument("--temperature", type=float, default=0.85)
    parser.add_argument("--top_p", type=float, default=0.85)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    config = LlamaConfig.from_pretrained(args.model_path)
    model = LlamaForCausalLM(config)

    import torch

    from fengshen_tpu.models.llama.convert import torch_to_params
    import os
    params = torch_to_params(
        torch.load(os.path.join(args.model_path, "pytorch_model.bin"),
                   map_location="cpu"), config)
    qparams = quantize_params_int8(params)
    print(f"int8 weights: {quantized_nbytes(qparams) / 1e9:.2f} GB")

    text = f"<human>:{args.prompt}\n<bot>:"
    ids = jnp.asarray([tokenizer.encode(text)], jnp.int32)
    out = quantized_generate(
        model, qparams, ids, max_new_tokens=args.max_new_tokens,
        do_sample=True, temperature=args.temperature, top_p=args.top_p,
        eos_token_id=tokenizer.eos_token_id)
    print(tokenizer.decode([int(t) for t in out[0]]))


if __name__ == "__main__":
    main()
