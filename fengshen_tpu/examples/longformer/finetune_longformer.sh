#!/bin/bash
# Launcher for longformer.finetune_longformer (reference pattern: fengshen/examples/longformer/*.sh)
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-Longformer-110M}
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}

python -m fengshen_tpu.examples.longformer.finetune_longformer \
    --model_path $MODEL_PATH \
    --train_file ${TRAIN_FILE:-train.json} \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize ${BATCH:-16} \
    --max_steps ${MAX_STEPS:-100000} \
    --learning_rate ${LR:-2e-5} \
    --warmup_steps 1000 \
    --every_n_train_steps 5000 \
    --precision bf16 \
    --max_seq_length 2048 --num_labels 2
