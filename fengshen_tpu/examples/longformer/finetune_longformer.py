"""Erlangshen-Longformer long-document classification finetune.

Port of the reference workload (reference: fengshen/examples/longformer/ —
long-document NLU with the sliding-window Longformer; first token carries
global attention).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.longformer import (
    LongformerConfig, LongformerForSequenceClassification)
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.trainer.module import TrainModule


@dataclass
class LongDocCollator:
    tokenizer: Any
    max_seq_length: int = 2048
    content_key: str = "text"

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        pad_id = tok.pad_token_id or 0
        max_len = self.max_seq_length
        batch = {"input_ids": [], "attention_mask": [],
                 "global_attention_mask": [], "labels": []}
        for s in samples:
            ids = [tok.cls_token_id] + tok.encode(
                s[self.content_key], add_special_tokens=False
            )[: max_len - 2] + [tok.sep_token_id]
            pad = max_len - len(ids)
            batch["input_ids"].append(ids + [pad_id] * pad)
            batch["attention_mask"].append([1] * len(ids) + [0] * pad)
            # [CLS] gets global attention (the longformer convention)
            batch["global_attention_mask"].append(
                [1] + [0] * (max_len - 1))
            batch["labels"].append(int(s.get("label", 0)))
        return {k: np.asarray(v) for k, v in batch.items()}


class LongformerClsModule(TrainModule):
    def __init__(self, args, config: Optional[LongformerConfig] = None):
        super().__init__(args)
        import dataclasses as dc
        if config is None and getattr(args, "model_path", None):
            config = LongformerConfig.from_pretrained(args.model_path)
        if config is None:
            raise ValueError("needs a config or --model_path")
        config = dc.replace(config, num_labels=args.num_labels)
        self.config = config
        self.model = LongformerForSequenceClassification(config)

    @staticmethod
    def add_module_specific_args(parent_parser):
        parser = parent_parser.add_argument_group("longformer finetune")
        parser.add_argument("--max_seq_length", type=int, default=2048)
        parser.add_argument("--num_labels", type=int, default=2)
        return parent_parser

    def init_params(self, rng):
        seq = min(self.args.max_seq_length, 32)
        ids = jnp.zeros((1, seq), jnp.int32)
        return self.model.init(rng, ids)["params"]

    def training_loss(self, params, batch, rng):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch["attention_mask"],
            global_attention_mask=batch["global_attention_mask"],
            deterministic=False, rngs={"dropout": rng})
        loss, _ = stable_cross_entropy(logits[:, None, :],
                                       batch["labels"][:, None])
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        return loss, {"acc": acc}

    def partition_rules(self):
        return self.model.partition_rules()


def main(argv=None):
    from transformers import AutoTokenizer

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = UniversalDataModule.add_data_specific_args(parser)
    parser = UniversalCheckpoint.add_argparse_args(parser)
    parser = LongformerClsModule.add_module_specific_args(parser)
    args = parser.parse_args(argv)

    tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    collator = LongDocCollator(tokenizer,
                               max_seq_length=args.max_seq_length)
    datamodule = UniversalDataModule(tokenizer=tokenizer,
                                     collate_fn=collator, args=args)
    module = LongformerClsModule(args)
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    trainer.fit(module, datamodule)


if __name__ == "__main__":
    main()
