"""CLUE-style classification evaluation harness.

Reference: fengshen/examples/clue1.1/ — the leaderboard recipe (the
reference's quality-parity bar in BASELINE.md). Evaluates a classification
pipeline (or a UniMC zero/few-shot pipeline) over CLUE-format jsonl and
reports accuracy per task.

    python -m fengshen_tpu.examples.clue1_1.evaluate_clue \
        --task tnews --data dev.json --model <dir> [--zero_shot]
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

#: task → (text field(s), label list or None for dataset-provided)
CLUE_TASKS = {
    "tnews": (("sentence",), None),
    "afqmc": (("sentence1", "sentence2"), ["不同", "相同"]),
    "iflytek": (("sentence",), None),
    "ocnli": (("sentence1", "sentence2"), ["矛盾", "中立", "蕴含"]),
    "cmnli": (("sentence1", "sentence2"), ["矛盾", "中立", "蕴含"]),
    "wsc": (("text",), ["否", "是"]),
    "csl": (("abst",), ["否", "是"]),
}


def load_clue_jsonl(path: str) -> list[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def evaluate_classification(pipeline, rows: list[dict], text_fields,
                            label_key: str = "label") -> float:
    """Accuracy of a TextClassificationPipeline over CLUE rows."""
    correct = total = 0
    for row in rows:
        texts = [row[f] for f in text_fields if f in row]
        pred = pipeline(texts[0], texts[1] if len(texts) > 1 else None)
        gold = row.get(label_key)
        if gold is None:
            continue
        total += 1
        correct += int(pred["label"] == int(gold))
    return correct / max(total, 1)


def evaluate_unimc(pipeline, rows: list[dict], choices: list[str],
                   text_fields, label_key: str = "label") -> float:
    """Zero/few-shot accuracy via the UniMC label-as-option pipeline."""
    data = []
    golds = []
    for row in rows:
        text = " ".join(str(row[f]) for f in text_fields if f in row)
        data.append({"texta": text, "choices": choices})
        golds.append(int(row.get(label_key, -1)))
    preds = pipeline.predict(data)
    pairs = [(p, g) for p, g in zip(preds, golds) if g >= 0]
    if not pairs:
        return 0.0
    return sum(int(p == g) for p, g in pairs) / len(pairs)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--task", required=True, choices=sorted(CLUE_TASKS))
    parser.add_argument("--data", required=True, type=str)
    parser.add_argument("--model", type=str, default=None)
    parser.add_argument("--zero_shot", action="store_true", default=False)
    args, rest = parser.parse_known_args(argv)

    text_fields, choices = CLUE_TASKS[args.task]
    rows = load_clue_jsonl(args.data)
    if args.zero_shot:
        from fengshen_tpu.models.unimc import UniMCPipelines
        pipe = UniMCPipelines(args=None, model=args.model)
        acc = evaluate_unimc(pipe, rows, choices or [], text_fields)
    else:
        from fengshen_tpu.pipelines.text_classification import (
            TextClassificationPipeline)
        pipe = TextClassificationPipeline(args=None, model=args.model)
        acc = evaluate_classification(pipe, rows, text_fields)
    print(json.dumps({"task": args.task, "accuracy": round(acc, 4),
                      "n": len(rows)}))
    return acc


if __name__ == "__main__":
    main()
