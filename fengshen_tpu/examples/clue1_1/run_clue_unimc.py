"""CLUE1.1 leaderboard recipe via UniMC.

Reference: fengshen/examples/clue1.1/run_clue_unimc.sh + solution/ —
every CLUE classification task reformulated as unified multiple choice
(the recipe behind the UniMC-DeBERTa CLUE1.1 rank-8 entry,
reference: fengshen/examples/clue1.1/README.md:3). Reads the CLUE json
files, maps each task's label ids onto option texts, trains through
UniMCPipelines, and writes leaderboard-format predictions — original
label-id strings for the fixed-label tasks, the reference
predict2submit formats for c3 (option indices) and chid (one
{tag: index} object).
"""

from __future__ import annotations

import argparse
import json
import os

# task → (ordered CLUE label ids, option texts). The label id at
# position i corresponds to choice i; predictions are written back as
# the original id string. ORDERING IS SHARED with the cluedata2unidata
# converters (their label2desc dict orders) so converted rows and these
# inline fallbacks agree on what option index i means.
TASK_LABELS = {
    "tnews": (["100", "101", "102", "103", "104", "106", "107", "108",
               "109", "110", "112", "113", "114", "115", "116"],
              ["故事", "文化", "娱乐", "体育", "财经", "房产", "汽车",
               "教育", "科技", "军事", "旅游", "国际", "股票", "农业",
               "电竞"]),
    "afqmc": (["0", "1"], ["不相似", "相似"]),
    "ocnli": (["contradiction", "neutral", "entailment"],
              ["矛盾", "自然", "蕴含"]),
    "csl": (["1", "0"], ["可以概括摘要", "不能概括摘要"]),
    "wsc": (["true", "false"], ["是", "不是"]),
    "iflytek": (None, None),  # built from the data / label_map.json
    # c3 and chid carry per-row choice lists (cluedata2unidata output
    # required); predictions are option indices with task-specific
    # submission formats (reference: predict2submit/{c3,chid}_submit.py)
    "c3": ([], []),
    "chid": ([], []),
}


def load_rows(path: str) -> list[dict]:
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    return rows


def iflytek_labels(rows: list[dict]) -> tuple[list[str], list[str]]:
    """label id → label_des vocabulary from the labelled splits."""
    seen: dict[str, str] = {}
    for r in rows:
        label = r.get("label")
        if label is not None:
            seen[str(label)] = r.get("label_des", str(label))
    ids = sorted(seen, key=lambda x: int(x) if x.isdigit() else 0)
    return ids, [seen[i] for i in ids]


def _text(task: str, r: dict) -> str:
    if task == "afqmc":
        return f"{r.get('sentence1', '')}[SEP]{r.get('sentence2', '')}"
    if task == "ocnli":
        return f"{r.get('sentence1', '')}[SEP]{r.get('sentence2', '')}"
    if task == "csl":
        return f"{r.get('abst', '')}[SEP]{','.join(r.get('keyword', []))}"
    if task == "wsc":
        t = r.get("target", {})
        return (f"{r.get('text', '')}[SEP]{t.get('span1_text', '')}"
                f"指代{t.get('span2_text', '')}")
    return r.get("sentence", r.get("text", ""))


def to_unimc(task: str, rows: list[dict], label_ids: list[str],
             choices: list[str]) -> list[dict]:
    if rows and "choice" in rows[0]:
        # already in the UniMC format (produced by cluedata2unidata's
        # reference-faithful per-task converters) — pass through
        return rows
    index = {lid: i for i, lid in enumerate(label_ids)}
    out = []
    for r in rows:
        item = {"texta": _text(task, r), "textb": "", "question": "",
                "choice": choices}
        label = r.get("label")
        if label is not None:
            item["label"] = index.get(str(label), 0)
        out.append(item)
    return out


def main(argv=None):
    from fengshen_tpu.models.unimc.modeling_unimc import UniMCPipelines

    parser = argparse.ArgumentParser()
    parser.add_argument("--task", default="tnews",
                        choices=list(TASK_LABELS))
    parser.add_argument("--data_dir", required=True)
    parser.add_argument("--output_path", default="predict.json")
    parser.add_argument("--train_data", default="train.json")
    parser.add_argument("--valid_data", default="dev.json")
    parser.add_argument("--test_data", default="test.json")
    parser.add_argument("--predict_batchsize", type=int, default=16)
    parser = UniMCPipelines.add_pipeline_specific_args(parser)
    args = parser.parse_args(argv)

    train_rows = load_rows(os.path.join(args.data_dir, args.train_data))
    dev_rows = load_rows(os.path.join(args.data_dir, args.valid_data))
    test_rows = load_rows(os.path.join(args.data_dir, args.test_data))

    label_ids, choices = TASK_LABELS[args.task]
    if args.task in ("c3", "chid"):
        # EVERY split must be pre-converted (per-row choice lists) —
        # raw c3/chid rows have no 'choice' and would silently train on
        # empty-option garbage through the generic fallback
        for name, rows in (("train", train_rows), ("dev", dev_rows),
                           ("test", test_rows)):
            if rows and "choice" not in rows[0]:
                raise ValueError(
                    f"{args.task} {name} split is not in the UniMC "
                    "format — run cluedata2unidata first")
        if not any((train_rows, dev_rows, test_rows)):
            raise ValueError(f"no data found for {args.task} in "
                             f"{args.data_dir}")
    if label_ids is None:
        label_map_path = os.path.join(args.data_dir, "label_map.json")
        if os.path.exists(label_map_path):
            # written by cluedata2unidata next to converted rows: the
            # original CLUE label id per option index
            with open(label_map_path, encoding="utf8") as f:
                label_map = json.load(f)
            label_ids = list(label_map)
            choices = list(label_map.values())
        else:
            label_ids, choices = iflytek_labels(train_rows + dev_rows)
        if not label_ids:
            raise ValueError(
                "iflytek needs label_map.json or labelled train/dev rows "
                "to build the label→description vocabulary")

    train = to_unimc(args.task, train_rows, label_ids, choices)
    dev = to_unimc(args.task, dev_rows, label_ids, choices)
    test = to_unimc(args.task, test_rows, label_ids, choices)

    pipe = UniMCPipelines(args, model=args.model_path)
    if train:
        pipe.train(train, dev or None)
    preds: list[int] = []
    bs = max(args.predict_batchsize, 1)
    for i in range(0, len(test), bs):
        preds.extend(pipe.predict(test[i:i + bs]))
    with open(args.output_path, "w") as f:
        if args.task == "chid":
            # submission is ONE json object {"#idiomN#": option_index}
            # (reference: predict2submit/chid_submit.py)
            f.write(json.dumps(
                {row.get("id"): int(p)
                 for row, p in zip(test_rows, preds)},
                ensure_ascii=False) + "\n")
        elif args.task == "c3":
            # c3 submits the option index directly
            # (reference: predict2submit/c3_submit.py)
            for row, p in zip(test_rows, preds):
                f.write(json.dumps(
                    {"id": row.get("id"), "label": int(p)},
                    ensure_ascii=False) + "\n")
        else:
            for row, p in zip(test_rows, preds):
                f.write(json.dumps(
                    {"id": row.get("id"), "label": label_ids[p]},
                    ensure_ascii=False) + "\n")
    print(f"[clue1.1:{args.task}] wrote {len(preds)} predictions "
          f"to {args.output_path}")


if __name__ == "__main__":
    main()
