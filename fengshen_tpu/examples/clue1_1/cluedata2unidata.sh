#!/bin/bash
# hparams carried from reference: fengshen/examples/clue1.1/cluedata2unidata.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
CLUEDATA_PATH=${CLUEDATA_PATH:-./CLUE_DATA}
UNIDATA_PATH=${UNIDATA_PATH:-./data}
for task in afqmc c3 chid csl iflytek ocnli tnews wsc; do
  case $task in
    wsc) in_dir=$CLUEDATA_PATH/cluewsc2020_public;;
    *)   in_dir=$CLUEDATA_PATH/${task}_public;;
  esac
  python -m fengshen_tpu.examples.clue1_1.cluedata2unidata \
      --task $task --input_dir $in_dir --output_dir $UNIDATA_PATH/$task
done
# cmrc2018 is extractive QA: served by the ubert recipe
# (run_clue_ubert.sh), not the UniMC converter.
