"""Raw CLUE1.1 json → the UniMC data format, per task.

Faithful restatement of the reference's per-task converters
(reference: fengshen/examples/clue1.1/data_preprocessing/
{tnews,afqmc,ocnli,csl,wsc,iflytek,c3,chid}_preprocessing.py and
cluedata2unidata.sh): the exact question strings, option texts, and
text augmentations those scripts produce are part of the published
recipe — the zero/few-shot numbers depend on them.

    python -m fengshen_tpu.examples.clue1_1.cluedata2unidata \
        --task tnews --input_dir ./CLUE/tnews --output_dir ./data/tnews
"""

from __future__ import annotations

import argparse
import json
import os

TNEWS_LABEL2DESC = {
    "news_story": "故事", "news_culture": "文化",
    "news_entertainment": "娱乐", "news_sports": "体育",
    "news_finance": "财经", "news_house": "房产", "news_car": "汽车",
    "news_edu": "教育", "news_tech": "科技", "news_military": "军事",
    "news_travel": "旅游", "news_world": "国际", "news_stock": "股票",
    "news_agriculture": "农业", "news_game": "电竞"}


def _rows(path):
    with open(path, encoding="utf8") as f:
        for line in f:
            if line.strip():
                yield json.loads(line)


_SKIP = object()  # row has a label the task cannot map (e.g. ocnli '-')


def _with_label(item: dict, data: dict, answer: str,
                choice: list) -> dict | object:
    """Attach label/answer only when resolvable. A PRESENT but unmapped
    label (OCNLI's no-consensus '-') signals the row must be DROPPED —
    emitting it as class 0 would train garbage; an ABSENT label (test
    split) emits the item without a label key."""
    if "label" not in data and "label_desc" not in data:
        item["answer"] = ""
        return item
    if not answer:
        return _SKIP
    item["answer"] = answer
    item["label"] = choice.index(answer)
    return item


def convert_tnews(data: dict) -> dict:
    choice = list(TNEWS_LABEL2DESC.values())
    answer = TNEWS_LABEL2DESC.get(data.get("label_desc", ""), "")
    item = {"texta": data["sentence"], "textb": "",
            "question": "下面新闻属于哪一个类别？", "choice": choice,
            "id": data.get("id", 0)}
    return _with_label(item, data, answer, choice)


def convert_afqmc(data: dict) -> dict:
    label2desc = {"0": "不相似", "1": "相似"}
    choice = list(label2desc.values())
    answer = label2desc.get(str(data.get("label", "")), "")
    item = {"texta": data["sentence1"], "textb": data["sentence2"],
            "question": "", "choice": choice,
            "id": data.get("id", 0)}
    return _with_label(item, data, answer, choice)


def convert_ocnli(data: dict) -> dict:
    label2desc = {"contradiction": "矛盾", "neutral": "自然",
                  "entailment": "蕴含"}
    choice = list(label2desc.values())
    answer = label2desc.get(data.get("label", ""), "")
    item = {"texta": data["sentence1"], "textb": data["sentence2"],
            "question": "", "choice": choice,
            "id": data.get("id", 0)}
    return _with_label(item, data, answer, choice)


def convert_csl(data: dict) -> dict:
    """jieba top-15 keywords prefixed to the abstract; options phrase the
    keyword list (reference: csl_preprocessing.py:16-47)."""
    import jieba.analyse

    label2desc = {"1": "可以", "0": "不能"}
    rs = jieba.analyse.extract_tags(data["abst"], topK=15)
    texta = "、".join(rs) + "。" + data["abst"]
    keyword = "、".join(data["keyword"])
    choice = [f"{v}使用{keyword}概括摘要" for v in label2desc.values()]
    answer = label2desc.get(str(data.get("label", "")), "")
    answer = f"{answer}使用{keyword}概括摘要" if answer else ""
    item = {"texta": texta, "textb": "", "question": "",
            "choice": choice, "id": data.get("id", 0)}
    return _with_label(item, data, answer, choice)


def convert_wsc(data: dict) -> dict:
    """Bracket span1 as [..] and span2 as _.._ in the text; options are
    '<span2>是/不是<span1>' (reference: wsc_preprocessing.py:10-45)."""
    label2desc = {"true": "是", "false": "不是"}
    target = data["target"]
    text = list(data["text"])
    s1, s2 = target["span1_index"], target["span2_index"]
    l1, l2 = len(target["span1_text"]), len(target["span2_text"])
    if s2 < s1:
        text.insert(s2, "_")
        text.insert(s2 + l2 + 1, "_")
        text.insert(s1 + 2, "[")
        text.insert(s1 + 2 + l1 + 1, "]")
    else:
        text.insert(s1, "[")
        text.insert(s1 + l1 + 1, "]")
        text.insert(s2 + 2, "_")
        text.insert(s2 + 2 + l2 + 1, "_")
    span1, span2 = target["span1_text"], target["span2_text"]
    choice = [f"{span2}{v}{span1}" for v in label2desc.values()]
    answer = label2desc.get(str(data.get("label", "")).lower(), "")
    answer = f"{span2}{answer}{span1}" if answer else ""
    item = {"texta": "".join(text), "textb": "", "question": "",
            "choice": choice, "id": data.get("id", 0)}
    return _with_label(item, data, answer, choice)


def convert_iflytek(data: dict, label_vocab: dict) -> dict:
    """Choices are the task's full label_des vocabulary, built from the
    labelled splits (the reference hardcodes the same list)."""
    choice = list(label_vocab.values())
    answer = label_vocab.get(str(data.get("label", "")), "")
    item = {"texta": data["sentence"], "textb": "",
            "question": "下面句子描述的应用属于哪一个类别？",
            "choice": choice, "id": data.get("id", 0)}
    return _with_label(item, data, answer, choice)


def convert_c3(data: list) -> list:
    """c3 rows are [passage_sentences, [qa...], id]; one UniMC item per
    question (reference: c3_preprocessing.py)."""
    texta = "\n".join(data[0])
    out = []
    for qa in data[1]:
        answer = qa.get("answer", "")
        item = {"texta": texta, "textb": "",
                "question": qa["question"], "choice": qa["choice"],
                "answer": answer,
                # per-QUESTION id (reference c3_preprocessing.py:20) —
                # the submission aligns predictions by it
                "id": qa.get("id", data[2] if len(data) > 2 else 0)}
        if answer:
            item["label"] = qa["choice"].index(answer)
        out.append(item)
    return out


def convert_chid(data: dict, answers: dict) -> list:
    """One UniMC item per idiom blank: the blank's sentence with #idiom#
    replaced by [MASK]s, candidates as options
    (reference: chid_preprocessing.py — simplified to whole-sentence
    context instead of its windowed re-segmentation)."""
    import re

    out = []
    for sent in data["content"]:
        for m in re.findall(r"#idiom\d+#", sent):
            # the scored blank becomes ____; OTHER blanks in the same
            # sentence are stripped so no raw #idiomN# junk remains
            text = re.sub(r"#idiom\d+#", "",
                          sent.replace(m, "____"))
            label = answers.get(m)
            item = {"texta": text, "textb": "", "question": "",
                    "choice": data["candidates"], "id": m}
            if label is not None:
                item["answer"] = data["candidates"][label]
                item["label"] = label
            else:
                item["answer"] = ""
            out.append(item)
    return out


def convert_file(task: str, in_path: str, out_path: str,
                 label_vocab: dict | None = None,
                 answers: dict | None = None) -> int:
    simple = {"tnews": convert_tnews, "afqmc": convert_afqmc,
              "ocnli": convert_ocnli, "csl": convert_csl,
              "wsc": convert_wsc}
    n = 0
    with open(out_path, "w", encoding="utf8") as out:
        for data in _rows(in_path):
            if task in simple:
                items = [simple[task](data)]
            elif task == "iflytek":
                items = [convert_iflytek(data, label_vocab or {})]
            elif task == "c3":
                items = convert_c3(data)
            elif task == "chid":
                items = convert_chid(data, answers or {})
            else:
                raise ValueError(f"unknown task {task}")
            for item in items:
                if item is _SKIP:
                    continue
                out.write(json.dumps(item, ensure_ascii=False) + "\n")
                n += 1
    return n


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="raw CLUE1.1 → UniMC-format jsonl")
    parser.add_argument("--task", required=True,
                        choices=["tnews", "afqmc", "ocnli", "csl", "wsc",
                                 "iflytek", "c3", "chid"])
    parser.add_argument("--input_dir", required=True)
    parser.add_argument("--output_dir", required=True)
    args = parser.parse_args(argv)
    os.makedirs(args.output_dir, exist_ok=True)

    label_vocab = None
    if args.task == "iflytek":
        label_vocab = {}
        for split in ("train.json", "dev.json"):
            path = os.path.join(args.input_dir, split)
            if os.path.exists(path):
                for r in _rows(path):
                    if "label" in r:
                        label_vocab[str(r["label"])] = r.get(
                            "label_des", str(r["label"]))
        label_vocab = dict(sorted(
            label_vocab.items(),
            key=lambda kv: int(kv[0]) if kv[0].isdigit() else 0))
    answers = None
    if args.task == "chid":
        answers = {}
        for name in ("train_answer.json", "dev_answer.json"):
            path = os.path.join(args.input_dir, name)
            if os.path.exists(path):
                with open(path, encoding="utf8") as f:
                    answers.update(json.load(f))

    if args.task == "iflytek" and label_vocab:
        # the original CLUE label id per option index — run_clue_unimc
        # reads this to write leaderboard-format predictions
        with open(os.path.join(args.output_dir, "label_map.json"), "w",
                  encoding="utf8") as f:
            json.dump(label_vocab, f, ensure_ascii=False, indent=1)

    for split in ("train.json", "dev.json", "test.json",
                  "test1.1.json", "test_public.json"):
        in_path = os.path.join(args.input_dir, split)
        if not os.path.exists(in_path):
            continue
        out_path = os.path.join(args.output_dir, split)
        n = convert_file(args.task, in_path, out_path, label_vocab,
                         answers)
        print(f"[{args.task}] {split}: {n} items → {out_path}")


if __name__ == "__main__":
    main()
