#!/bin/bash
# CLUE1.1 leaderboard recipe via UniMC (reference:
# fengshen/examples/clue1.1/run_clue_unimc.sh — tnews/afqmc/iflytek/
# wsc/ocnli/csl/chid/c3 as unified multiple choice)
set -euo pipefail

TASK=${TASK:-tnews}
DATA_DIR=${DATA_DIR:-./data/$TASK}
MODEL_PATH=${MODEL_PATH:-IDEA-CCNL/Erlangshen-UniMC-RoBERTa-110M-Chinese}
ROOT_DIR=${ROOT_DIR:-./workdir/clue11_unimc_$TASK}
mkdir -p $ROOT_DIR

python -m fengshen_tpu.examples.clue1_1.run_clue_unimc \
    --task $TASK \
    --data_dir $DATA_DIR \
    --model_path $MODEL_PATH \
    --default_root_dir $ROOT_DIR \
    --save_ckpt_path $ROOT_DIR/ckpt \
    --load_ckpt_path $ROOT_DIR/ckpt \
    --train_batchsize 16 \
    --max_length 512 \
    --learning_rate 2e-5 \
    --max_epochs 7 \
    --precision bf16 \
    --output_path $ROOT_DIR/${TASK}_predict.json
