"""CLUE1.1 predictions → leaderboard submission files.

Port of the reference's per-task submit scripts
(reference: fengshen/examples/clue1.1/predict2submit/{afqmc,tnews,
iflytek,ocnli,csl,wsc,c3,chid,cmrc2018}_submit.py — one small script per
task, unified here behind ``--task``). Input rows are prediction jsonl in
the reference format: ``{id, choice, label, score{choice: p}}`` (+
``line_id`` for chid groups; ubert entity lists for cmrc2018).

Note: `run_clue_unimc.py` already writes leaderboard-format predictions
directly; this driver exists for reference-format predict files and for
the tasks whose submissions need cross-row re-grouping (csl voting,
chid exclusive assignment).
"""

from __future__ import annotations

import argparse
import json
from typing import Any

import numpy as np

from fengshen_tpu.examples.clue1_1.cluedata2unidata import TNEWS_LABEL2DESC

#: CLUE tnews submission codes per label name
#: (reference: predict2submit/tnews_submit.py:8-23 id2label)
TNEWS_CODES = {
    "news_story": "100", "news_culture": "101",
    "news_entertainment": "102", "news_sports": "103",
    "news_finance": "104", "news_house": "106", "news_car": "107",
    "news_edu": "108", "news_tech": "109", "news_military": "110",
    "news_travel": "112", "news_world": "113", "news_stock": "114",
    "news_agriculture": "115", "news_game": "116"}
#: option desc → submission code (composed through the shared forward
#: table so the two stay consistent)
TNEWS_DESC2CODE = {desc: TNEWS_CODES[name]
                   for name, desc in TNEWS_LABEL2DESC.items()}


def _rows(path: str) -> list[dict]:
    with open(path, encoding="utf8") as f:
        return [json.loads(line) for line in f if line.strip()]


def _write_jsonl(rows: list[dict], path: str) -> None:
    with open(path, "w", encoding="utf8") as f:
        for row in rows:
            f.write(json.dumps(row, ensure_ascii=False) + "\n")


def _write_json(data: Any, path: str) -> None:
    with open(path, "w", encoding="utf8") as f:
        f.write(json.dumps(data, ensure_ascii=False) + "\n")


def exclusive_assign(group: list[dict]) -> list[dict]:
    """Greedy one-option-per-row assignment by descending score — the
    reference's `recls` (chid candidates are used exactly once per
    group; reference: chid_submit.py:20-33)."""
    mat = np.asarray([[v for v in row["score"].values()]
                      for row in group], np.float64)
    n_rows, n_labels = mat.shape
    for _ in range(n_rows):
        i, j = np.unravel_index(np.argmax(mat), mat.shape)
        group[i]["label"] = int(j)
        mat[i, :] = 0.0
        mat[:, j] = 0.0
    return group


def submit_afqmc(rows: list[dict]) -> list[dict]:
    id2label = {0: "0", 1: "1"}
    return [{"id": r["id"], "label": id2label[int(r["label"])]}
            for r in rows]


def submit_tnews(rows: list[dict]) -> list[dict]:
    return [{"id": r["id"],
             "label": TNEWS_DESC2CODE[r["choice"][int(r["label"])]]}
            for r in rows]


def submit_iflytek(rows: list[dict], label_map: dict) -> list[dict]:
    """label_map (cluedata2unidata's label_map.json): original CLUE
    label id → option desc; inverted here (reference hardcodes the same
    two tables, iflytek_submit.py:6-130)."""
    desc2id = {desc: lid for lid, desc in label_map.items()}
    return [{"id": r["id"],
             "label": desc2id[r["choice"][int(r["label"])]]}
            for r in rows]


def submit_ocnli(rows: list[dict]) -> list[dict]:
    id2label = {0: "contradiction", 1: "neutral", 2: "entailment"}
    return [{"id": r["id"], "label": id2label[int(r["label"])]}
            for r in rows]


def submit_wsc(rows: list[dict]) -> list[dict]:
    """Option order decides the true/false mapping
    (reference: wsc_submit.py:8-21)."""
    out = []
    for r in rows:
        if "不是" in r["choice"][0] and "是" in r["choice"][1]:
            label = "false" if int(r["label"]) == 1 else "true"
        else:
            label = "true" if int(r["label"]) == 0 else "false"
        out.append({"id": r["id"], "label": label})
    return out


def submit_c3(rows: list[dict]) -> list[dict]:
    return [{"id": r["id"], "label": int(r["label"])} for r in rows]


def submit_csl(rows: list[dict]) -> list[dict]:
    """Abstract-level vote: within each texta group, the higher-scored
    half of the keyword rows is class 0 ('可以'), the rest class 1,
    then 1↦'0'/0↦'1' for the leaderboard
    (reference: csl_submit.py:40-72 csl_scorted + submit)."""
    groups: dict[str, dict] = {}
    for r in rows:
        groups.setdefault(r["texta"], {})[r["id"]] = \
            r["score"][r["choice"][0]]
    id2label = {}
    for scores in groups.values():
        ranked = sorted(scores.items(), key=lambda kv: kv[1],
                        reverse=True)
        for i, (row_id, _) in enumerate(ranked):
            id2label[row_id] = 0 if i < len(ranked) / 2 else 1
    flip = {1: "0", 0: "1"}
    return [{"id": r["id"], "label": flip[id2label[r["id"]]]}
            for r in rows]


def submit_chid(rows: list[dict]) -> dict:
    """Group rows by line_id, exclusively assign candidates within each
    group, emit {blank_tag: option_index}
    (reference: chid_submit.py:41-57)."""
    groups: dict[Any, list] = {}
    for r in rows:
        groups.setdefault(r.get("line_id", r["id"]), []).append(r)
    result = {}
    for group in groups.values():
        for r in exclusive_assign(group):
            result[r["id"]] = int(r["label"])
    return result


def submit_cmrc2018(rows: list[dict]) -> dict:
    """ubert entity predictions → best span per question id
    (reference: cmrc2018_submit.py:7-27)."""
    id2spans: dict[Any, list] = {}
    for row in rows:
        for choice in row["choices"]:
            id2spans.setdefault(choice["id"], []).extend(
                choice.get("entity_list", []))
    return {qid: (sorted(spans, key=lambda s: s["score"],
                         reverse=True)[0]["entity_name"] if spans else "")
            for qid, spans in id2spans.items()}


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="CLUE predictions → submission format")
    parser.add_argument("--task", required=True,
                        choices=["afqmc", "tnews", "iflytek", "ocnli",
                                 "csl", "wsc", "c3", "chid", "cmrc2018"])
    parser.add_argument("--data_path", required=True, type=str)
    parser.add_argument("--save_path", required=True, type=str)
    parser.add_argument("--label_map", default=None, type=str,
                        help="iflytek: cluedata2unidata's label_map.json")
    args = parser.parse_args(argv)

    rows = _rows(args.data_path)
    if args.task == "iflytek":
        if not args.label_map:
            parser.error("--task iflytek requires --label_map")
        with open(args.label_map, encoding="utf8") as f:
            result = submit_iflytek(rows, json.load(f))
    elif args.task in ("chid", "cmrc2018"):
        result = {"chid": submit_chid,
                  "cmrc2018": submit_cmrc2018}[args.task](rows)
    else:
        result = {"afqmc": submit_afqmc, "tnews": submit_tnews,
                  "ocnli": submit_ocnli, "csl": submit_csl,
                  "wsc": submit_wsc, "c3": submit_c3}[args.task](rows)

    if isinstance(result, dict):
        _write_json(result, args.save_path)
    else:
        _write_jsonl(result, args.save_path)
    print(f"[{args.task}] {len(rows)} predictions → {args.save_path}")


if __name__ == "__main__":
    main()
