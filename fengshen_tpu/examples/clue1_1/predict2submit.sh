#!/bin/bash
# hparams carried from reference: fengshen/examples/clue1.1/predict2submit.sh
# TPU-native translation: DeepSpeed ZeRO -> mesh flags, fp16 -> bf16.
set -euo pipefail
ROOT_DIR=${ROOT_DIR:-./workdir/$(basename $0 .sh)}
mkdir -p $ROOT_DIR
PRED_DATA_PATH=${PRED_DATA_PATH:-./predict}
SUBMIT_DATA_PATH=${SUBMIT_DATA_PATH:-./submit}
mkdir -p $SUBMIT_DATA_PATH
python -m fengshen_tpu.examples.clue1_1.predict2submit --task afqmc \
    --data_path $PRED_DATA_PATH/afqmc_predict.json \
    --save_path $SUBMIT_DATA_PATH/afqmc_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task c3 \
    --data_path $PRED_DATA_PATH/c3_predict.json \
    --save_path $SUBMIT_DATA_PATH/c311_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task chid \
    --data_path $PRED_DATA_PATH/chid_predict.json \
    --save_path $SUBMIT_DATA_PATH/chid11_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task csl \
    --data_path $PRED_DATA_PATH/csl_predict.json \
    --save_path $SUBMIT_DATA_PATH/csl_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task iflytek \
    --data_path $PRED_DATA_PATH/iflytek_predict.json \
    --label_map $PRED_DATA_PATH/iflytek_label_map.json \
    --save_path $SUBMIT_DATA_PATH/iflytek_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task ocnli \
    --data_path $PRED_DATA_PATH/ocnli_predict.json \
    --save_path $SUBMIT_DATA_PATH/ocnli_50k_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task tnews \
    --data_path $PRED_DATA_PATH/tnews_predict.json \
    --save_path $SUBMIT_DATA_PATH/tnews11_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task wsc \
    --data_path $PRED_DATA_PATH/wsc_predict.json \
    --save_path $SUBMIT_DATA_PATH/cluewsc11_predict.json
python -m fengshen_tpu.examples.clue1_1.predict2submit --task cmrc2018 \
    --data_path $PRED_DATA_PATH/cmrc2018_predict.json \
    --save_path $SUBMIT_DATA_PATH/cmrc2018_predict.json
