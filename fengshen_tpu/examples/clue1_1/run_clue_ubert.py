"""CLUE1.1 extraction-style recipe via UBERT.

Reference: fengshen/examples/clue1.1/run_clue_ubert.sh — span-extraction
tasks (cmrc-style reading comprehension) driven through the UBERT
instruction format: {task_type, text, choices: [{entity_type}]}.
"""

from __future__ import annotations

import argparse
import json
import os


def load_rows(path: str) -> list[dict]:
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    return rows


def to_ubert(rows: list[dict]) -> list[dict]:
    out = []
    for r in rows:
        question = r.get("question", r.get("query", "答案"))
        item = {
            "task_type": "抽取任务",
            "subtask_type": "抽取式阅读理解",
            "text": r.get("context", r.get("text", "")),
            "choices": [{"entity_type": question,
                         "entity_list": [
                             {"entity_name": a.get("text", ""),
                              "entity_idx": [[a.get("answer_start", 0),
                                              a.get("answer_start", 0) +
                                              max(len(a.get("text", "")) -
                                                  1, 0)]]}
                             for a in r.get("answers", [])]}],
        }
        out.append(item)
    return out


def main(argv=None):
    from fengshen_tpu.models.ubert.modeling_ubert import UbertPipelines

    parser = argparse.ArgumentParser()
    parser.add_argument("--task", default="cmrc")
    parser.add_argument("--data_dir", required=True)
    parser.add_argument("--output_path", default="predict.json")
    parser.add_argument("--train_data", default="train.json")
    parser.add_argument("--valid_data", default="dev.json")
    parser.add_argument("--test_data", default="test.json")
    parser = UbertPipelines.pipelines_args(parser)
    args = parser.parse_args(argv)

    train = to_ubert(load_rows(
        os.path.join(args.data_dir, args.train_data)))
    dev = to_ubert(load_rows(os.path.join(args.data_dir, args.valid_data)))
    test_rows = load_rows(os.path.join(args.data_dir, args.test_data))
    test = to_ubert(test_rows)

    pipe = UbertPipelines(args, model=args.model_path)
    if train:
        pipe.fit(train, dev or None)
    preds = pipe.predict(test) if test else []
    with open(args.output_path, "w") as f:
        for row, p in zip(test_rows, preds):
            answers = [e["entity_name"]
                       for ch in p.get("choices", [])
                       for e in ch.get("entity_list", [])]
            f.write(json.dumps(
                {"id": row.get("id"), "answer": answers[0] if answers
                 else ""}, ensure_ascii=False) + "\n")
    print(f"[clue1.1:{args.task}] wrote {len(preds)} predictions "
          f"to {args.output_path}")


if __name__ == "__main__":
    main()
