"""Replica-side disaggregation coordinator: the glue between the api
layer, the engine's lane handoff (`serving/handoff.py`) and the
transfer plane (`disagg/transfer.py`).

One coordinator rides on every continuous-engine replica and plays
both sides of a handoff:

- **prefill side** (`handoff()`): after the api layer submits a
  request the router tagged with a `disagg_push_to` target, wait for
  the lane to prime (prefill + first token), export it, PUT it to the
  decode peer, and — only once the peer ACKs adoption — detach the
  local lane and hand the router a redirect body. EVERY failure mode
  (lane never primed, export refused, connect/timeout, size cap,
  adopt-decline, local decode winning the race) degrades to plain
  local decode: the method returns None, the api layer falls through
  to its normal wait, and the client sees an ordinary 200. Fallbacks
  are counted per reason in `fstpu_disagg_fallbacks_total{reason}` and
  stamped onto the request timeline, so the assembled fleet trace
  shows exactly where a handoff died.
- **decode side** (`handle_put`/`handle_get`/`handle_delete`): adopt
  pushed lanes into the local engine, park the resumed Request in a
  bounded registry, and serve the router's collect long-poll with the
  same generate-shaped body the prefill replica would have produced.

The coordinator owns its own `MetricsRegistry` (`fstpu_disagg_*`
series), which the api layer concatenates into `GET /metrics` — the
same pattern as the engine's registry.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from fengshen_tpu.disagg import transfer
from fengshen_tpu.observability import MetricsRegistry, span
from fengshen_tpu.serving import handoff
from fengshen_tpu.serving.engine import FINISHED, RUNNING

#: adopted requests kept for collection; a decode replica whose
#: collects all die still bounds its registry
MAX_ADOPTED = 256


class DisaggCoordinator:
    """Per-replica handoff orchestration (see module docstring)."""

    def __init__(self, engine, pipeline,
                 push_timeout_s: float = 10.0,
                 max_payload_bytes: int =
                 transfer.DEFAULT_MAX_PAYLOAD_BYTES,
                 transport=None,
                 log: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 registry: Optional[MetricsRegistry] = None):
        self.engine = engine
        self.pipeline = pipeline
        self.push_timeout_s = float(push_timeout_s)
        self.max_payload_bytes = int(max_payload_bytes)
        #: fleet-style ``request(base_url, method, path, body,
        #: timeout_s)`` override; None = the stdlib urllib push (the
        #: fault-injection seam)
        self.transport = transport
        self._log = log or (lambda entry: None)
        self._clock = clock
        self._sleep = sleep
        r = self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._c_handoffs = r.counter(
            "fstpu_disagg_handoffs_total",
            "prefill-side handoff attempts by outcome "
            "(redirected / local_finish / fallback)",
            labelnames=("outcome",))
        self._c_fallbacks = r.counter(
            "fstpu_disagg_fallbacks_total",
            "handoffs degraded to local decode, by failure reason",
            labelnames=("reason",))
        self._c_adopted = r.counter(
            "fstpu_disagg_adopted_total",
            "lanes adopted into this replica's engine")
        self._c_declined = r.counter(
            "fstpu_disagg_adopt_declined_total",
            "adoption refusals by reason", labelnames=("reason",))
        self._c_payload_bytes = r.counter(
            "fstpu_disagg_payload_bytes_total",
            "encoded bytes of exported lane payloads")
        self._h_push = r.histogram(
            "fstpu_disagg_push_seconds",
            "wall seconds of the replica-to-replica KV push")
        self._c_evac = r.counter(
            "fstpu_evac_lanes_total",
            "drain-time live lane evacuations by outcome "
            "(adopted / fallback / local_finish)",
            labelnames=("outcome",))
        self._lock = threading.Lock()
        self._adopted: Dict[str, Any] = {}

    # ---- prefill side ----------------------------------------------

    def handoff(self, request, push_to: str) -> Optional[dict]:
        """Try to hand `request` (already submitted to the local
        engine) to the decode replica at base url `push_to`. Returns
        the redirect body for the router on success, None when the
        request should (keep) running locally — which is NEVER a
        client-visible error."""
        rid = request.request_id
        deadline = self._clock() + self.push_timeout_s
        # the lane must prime first: prefill + first token happen on
        # the scheduler thread; a paged engine may also defer admission
        # on block pressure, which this wait absorbs up to the budget
        while (not request.done and request.state != RUNNING and
               self._clock() < deadline):
            self._sleep(0.002)
        if request.done:
            self._c_handoffs.labels("local_finish").inc()
            return None
        if request.state != RUNNING:
            return self._fallback(request, "not_running")
        try:
            with span("disagg/export"):
                payload = handoff.export_lane(self.engine, rid)
        except handoff.HandoffError as e:
            return self._fallback(request, "export", error=str(e))
        self._c_payload_bytes.inc(transfer.payload_nbytes(payload))
        t0 = self._clock()
        try:
            with span("disagg/push"):
                transfer.push_payload(
                    push_to, rid, payload,
                    timeout_s=max(deadline - self._clock(), 0.05),
                    max_bytes=self.max_payload_bytes,
                    transport=self.transport)
        except transfer.KvPushError as e:
            if e.sent:
                # the peer MAY hold an adopted twin (wedged push,
                # declined-after-adopt races): cancel it so one request
                # never decodes twice to completion
                self._delete_twin(push_to, rid)
            return self._fallback(request, e.reason, error=str(e))
        self._h_push.observe(self._clock() - t0)
        if not handoff.detach_lane(self.engine, rid, target=push_to):
            # local decode finished during the push; the local result
            # stands and the adopted twin is cancelled
            self._delete_twin(push_to, rid)
            self._c_handoffs.labels("local_finish").inc()
            return None
        self._c_handoffs.labels("redirected").inc()
        self._log({"event": "disagg_redirect", "request_id": rid,
                   "target": push_to})
        return {"disagg_redirect": True, "request_id": rid,
                "target": push_to}

    def _fallback(self, request, reason: str,
                  error: Optional[str] = None) -> None:
        self._c_fallbacks.labels(reason).inc()
        self._c_handoffs.labels("fallback").inc()
        # the fallback mark joins the request's own timeline, so the
        # assembled fleet trace shows the failed handoff inline with
        # the decode that absorbed it
        request.timeline.add(self.engine._clock(), "handoff_fallback",
                             reason=reason)
        self._log({"event": "disagg_fallback", "reason": reason,
                   "request_id": request.request_id,
                   "error": (error or "")[:200]})
        return None

    # ---- live evacuation (docs/fault_tolerance.md) ------------------

    def evacuate_all(self, peers,
                     probe_timeout_s: float = 2.0) -> dict:
        """Drain-time lane rescue: export every RUNNING lane and push
        it to the healthiest willing peer (`policy.plan_evacuation`
        ranks the probed candidates). Runs on the drain waiter thread,
        strictly OFF the engine lock around every HTTP call — the
        lanes keep decoding while their snapshots travel, which is
        safe for the same reason `handoff()` is: greedy decode from
        the snapshot cursor reproduces the identical tail.

        Per-lane outcomes (counted in
        `fstpu_evac_lanes_total{outcome}`):

        - ``adopted``: a peer adopted; the lane is detached as
          `evacuated` and the blocked POST answers with a redirect the
          router re-collects from the adopter;
        - ``local_finish``: the lane finished (or left) before the
          push landed — the local result stands;
        - ``fallback``: no peer would take it — the lane keeps
          decoding here to completion, NEVER an error (the drain
          waiter simply waits for it like before).
        """
        lane_ids = self.engine.live_lane_ids()
        summary = {"lanes": len(lane_ids), "adopted": 0,
                   "fallback": 0, "local_finish": 0}
        if not lane_ids:
            return summary
        candidates = []
        for url in peers:
            stats = self._probe_peer(url, probe_timeout_s)
            if stats is None:
                continue        # unreachable peers never rank
            candidates.append({
                "url": url,
                "draining": bool(stats.get("draining") or False),
                "phase": str(stats.get("phase") or "both"),
                "slots_active": int(stats.get("slots_active") or 0),
                "num_slots": int(stats.get("num_slots") or 0),
                "queue_depth": int(stats.get("queue_depth") or 0)})
        from fengshen_tpu.disagg import policy
        targets = policy.plan_evacuation(candidates)
        for rid in lane_ids:
            outcome = self._evacuate_lane(rid, targets)
            self._c_evac.labels(outcome).inc()
            summary[outcome] += 1
        self._log({"event": "disagg_evacuate", **summary,
                   "targets": len(targets)})
        return summary

    def _evacuate_lane(self, rid: str, targets) -> str:
        try:
            with span("disagg/export"):
                payload = handoff.export_lane(self.engine, rid)
        except handoff.HandoffError:
            # finished (or left the pool) between snapshot and export
            return "local_finish"
        self._c_payload_bytes.inc(transfer.payload_nbytes(payload))
        for url in targets:
            t0 = self._clock()
            try:
                with span("disagg/push"):
                    transfer.push_payload(
                        url, rid, payload,
                        timeout_s=self.push_timeout_s,
                        max_bytes=self.max_payload_bytes,
                        transport=self.transport)
            except transfer.KvPushError as e:
                if e.sent:
                    # same twin hazard as handoff(): the peer MAY hold
                    # an adopted copy behind the lost ack
                    self._delete_twin(url, rid)
                self._log({"event": "disagg_evacuate_push_failed",
                           "request_id": rid, "target": url,
                           "reason": e.reason})
                continue        # next-best peer
            self._h_push.observe(self._clock() - t0)
            if not handoff.detach_lane(self.engine, rid, target=url,
                                       evacuated=True):
                # local decode finished during the push; its result
                # stands and the adopted twin is cancelled
                self._delete_twin(url, rid)
                return "local_finish"
            self._log({"event": "disagg_evacuated", "request_id": rid,
                       "target": url})
            return "adopted"
        # no willing peer: the lane keeps decoding locally — mark the
        # degradation on its timeline so the assembled trace shows the
        # rescue that didn't happen
        with self.engine._cv:
            for r in self.engine._slot_req:
                if r is not None and r.request_id == rid:
                    r.timeline.add(self.engine._clock(),
                                   "evac_fallback",
                                   peers_probed=len(targets))
                    break
        return "fallback"

    def _probe_peer(self, url: str,
                    timeout_s: float) -> Optional[dict]:
        """GET /stats from one candidate peer; None when unreachable
        or non-200 (an unreachable peer must cost one short timeout,
        never an exception on the drain path)."""
        try:
            if self.transport is not None:
                code, body = self.transport.request(
                    url, "GET", "/stats", None, timeout_s)
            else:
                import json
                import urllib.request
                with urllib.request.urlopen(
                        url.rstrip("/") + "/stats",
                        timeout=timeout_s) as r:
                    code, body = r.status, json.loads(r.read())
            return body if code == 200 and isinstance(body, dict) \
                else None
        except Exception:  # noqa: BLE001 — probe failures just
            return None    # exclude the peer from ranking

    def _delete_twin(self, push_to: str, rid: str) -> None:
        """Best-effort DELETE of a possibly-adopted twin; failures are
        logged and swallowed (the twin also dies at its deadline)."""
        try:
            if self.transport is not None:
                self.transport.request(push_to, "DELETE", f"/kv/{rid}",
                                       body=None, timeout_s=2.0)
            else:
                import urllib.request
                req = urllib.request.Request(
                    push_to.rstrip("/") + f"/kv/{rid}", method="DELETE")
                urllib.request.urlopen(req, timeout=2.0).read()
        except Exception as e:  # noqa: BLE001 — best-effort cleanup
            self._log({"event": "disagg_twin_delete_failed",
                       "request_id": rid, "error": str(e)[:200]})

    # ---- decode side -----------------------------------------------

    def handle_put(self, rid: str, payload: Any) -> tuple[int, dict]:
        """PUT /kv/<rid>: adopt a pushed lane. 200 + adopted ack, or a
        409 decline with the reason the source's fallback counter
        labels."""
        if not isinstance(payload, dict):
            self._c_declined.labels("payload_invalid").inc()
            return 409, {"adopted": False, "reason": "payload_invalid",
                         "error": "payload must be a JSON object"}
        if payload.get("request_id") != rid:
            self._c_declined.labels("payload_invalid").inc()
            return 409, {"adopted": False, "reason": "payload_invalid",
                         "error": "request_id mismatch with path"}
        try:
            with span("disagg/adopt"):
                req = handoff.adopt_lane(self.engine, payload)
        except handoff.AdoptDecline as e:
            self._c_declined.labels(e.reason).inc()
            self._log({"event": "disagg_adopt_declined",
                       "request_id": rid, "reason": e.reason})
            return 409, {"adopted": False, "reason": e.reason,
                         "error": str(e)}
        except Exception as e:  # noqa: BLE001 — an adopt crash must
            # answer (the source falls back to local decode), not
            # drop the socket
            self._c_declined.labels("internal").inc()
            return 500, {"adopted": False, "reason": "internal",
                         "error": str(e)[:200]}
        with self._lock:
            if len(self._adopted) >= MAX_ADOPTED:
                # evict the oldest uncollected entry (its engine-side
                # request keeps running to its own finish)
                self._adopted.pop(next(iter(self._adopted)))
            self._adopted[rid] = req
        self._c_adopted.inc()
        return 200, {"adopted": True, "request_id": rid}

    def handle_get(self, rid: str,
                   timeout_s: float) -> tuple[int, dict]:
        """GET /kv/<rid>: long-poll for an adopted request's result —
        the generate-shaped body the router forwards to the client."""
        with self._lock:
            req = self._adopted.get(rid)
        if req is None:
            return 404, {"error": f"unknown adopted request {rid!r}"}
        if not req.wait(timeout=timeout_s):
            return 504, {"error": f"adopted request {rid!r} still "
                                  f"decoding after {timeout_s}s"}
        with self._lock:
            self._adopted.pop(rid, None)
        if req.state != FINISHED:
            return 503, {"error": f"adopted request {req.state} "
                                  f"({req.finish_reason})"}
        return 200, {"result": self.pipeline.decode(req.tokens),
                     "request_id": req.request_id,
                     "ttft_s": req.ttft_s,
                     "finish_reason": req.finish_reason,
                     "adopted": True}

    def handle_delete(self, rid: str) -> tuple[int, dict]:
        """DELETE /kv/<rid>: cancel an adopted twin (the source won
        the race or gave up on a wedged push)."""
        with self._lock:
            req = self._adopted.pop(rid, None)
        cancelled = self.engine.cancel(rid) if req is not None else False
        return 200, {"cancelled": bool(cancelled)}

    def adopted_count(self) -> int:
        with self._lock:
            return len(self._adopted)
