"""Disaggregation microbench: prefill/decode-split fleet tokens/s vs a
homogeneous fleet, plus the adopt-decline fallback rung.

    make serve-bench-disagg
    DISAGG_BENCH_PREFILL=2 DISAGG_BENCH_DECODE=2 \
        python -m fengshen_tpu.disagg.bench

Three rungs over ONE mixed long-prompt/short-decode request set
(docs/disaggregation.md):

1. **homogeneous**: `HOMOGENEOUS` both-phase replicas behind a
   `FleetRouter` → `tokens_per_sec_homogeneous` (the baseline);
2. **disagg**: `PREFILL` prefill-tier + `DECODE` decode-tier replicas
   behind the same router — phase-aware placement primes each lane on
   the prefill tier, pushes its KV to the decode tier, and the router
   collects the decode tail (`value`; the acceptance bar is
   disagg >= homogeneous on this workload shape). Outputs must be
   token-identical to rung 1's;
3. **fallback** (fake lane only): the same disagg topology with every
   decode replica DECLINING adoption — every request must still answer
   200 with token-identical output (local prefill-and-decode on the
   originating replica), and the fallback count must equal the request
   count.

One BENCH-schema JSON line with the **topology in the row**
(`"topology": "prefill=P,decode=D"`): benchdiff folds topology into
the comparison identity, so disaggregated rounds never diff against
homogeneous or differently-split ones.

`FLEET_BENCH_FAKE=1` (or `DISAGG_BENCH_FAKE=1`) swaps the replicas for
in-process fake servers (pure stdlib, no jax) whose cost model keeps
the one thing the bench measures: a both-phase replica pays a
**phase-switch interference cost** on every prefill (the running
decode batch stalls while the prefill monopolizes the chip — the
exact cost disaggregation removes), while a prefill-tier replica pays
raw prefill only and a decode-tier replica's batch is never
interrupted. The fakes speak the full transfer-plane shape (`PUT` /
`GET` / `DELETE /kv/<id>`, adopt acks, declines), so the REAL router +
placement policy + redirect/collect path is exercised end to end in
the fast-lane smoke test (`tests/test_disagg_bench_smoke.py`).

Env knobs (DISAGG_BENCH_*, falling back to FLEET_BENCH_* where both
exist): PREFILL, DECODE, HOMOGENEOUS, REQUESTS, NEW_TOKENS, SLOTS,
PROMPT_LEN, FAKE, FAKE_TOKEN_S, FAKE_PREFILL_S (per prompt token),
FAKE_SWITCH_S, BASE_PORT, SEED, plus fleet.bench's model-shape knobs
for the real-replica path (VOCAB / HIDDEN / INTER / LAYERS / HEADS /
BUCKETS).
"""

from __future__ import annotations

import argparse
import http.server
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from typing import List, Tuple

from fengshen_tpu.fleet.bench import (_IntTokenizer, _buckets, _drive,
                                      _emit, _fake_result,
                                      _make_router)


def _env(name: str, default: int) -> int:
    v = os.environ.get(f"DISAGG_BENCH_{name}",
                       os.environ.get(f"FLEET_BENCH_{name}"))
    return default if v is None else int(v)


def _fenv(name: str, default: float) -> float:
    v = os.environ.get(f"DISAGG_BENCH_{name}",
                       os.environ.get(f"FLEET_BENCH_{name}"))
    return default if v is None else float(v)


# ---- fake phase replicas (the harness-smoke fast lane) --------------

def _fake_push(push_to: str, rid: str, ids: List[int],
               n: int) -> bool:
    """The fake prefill side's KV push: same verb + path + ack contract
    as the real transfer plane, fake payload (there is no engine)."""
    body = json.dumps({"request_id": rid, "ids": ids, "n": n}).encode()
    req = urllib.request.Request(
        push_to.rstrip("/") + f"/kv/{rid}", data=body, method="PUT",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10.0) as r:
            return bool(json.loads(r.read()).get("adopted"))
    except Exception:  # noqa: BLE001 — any push failure = fall back
        return False


def start_fake_phase_replica(phase: str, num_slots: int,
                             token_s: float, prefill_per_tok_s: float,
                             switch_s: float, default_new_tokens: int,
                             decline: bool = False,
                             host: str = "127.0.0.1", port: int = 0):
    """In-process fake replica speaking the api + transfer surface for
    one serving phase. Cost model: prefill monopolizes the chip
    (exclusive lock, `len(prompt) * prefill_per_tok_s`), PLUS
    `switch_s` interference on a both-phase replica (the stalled
    decode batch); decode sleeps `n * token_s` gated by a
    num_slots-wide semaphore and is never interrupted. `decline=True`
    turns a decode replica into an adopt-decliner (the fallback rung).
    Returns (server, thread, counters)."""
    chip = threading.Lock()
    sem = threading.BoundedSemaphore(num_slots)
    lock = threading.Lock()
    active = [0]
    counters = {"fallbacks": 0, "redirects": 0, "adopted": 0,
                "declined": 0}
    adopted: dict = {}

    def decode_sleep(n: int) -> None:
        with sem:
            time.sleep(n * token_s)

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read(self):
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}")

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok", "ready": True})
            elif self.path == "/stats":
                with lock:
                    a = active[0]
                self._send(200, {"slots_active": min(a, num_slots),
                                 "queue_depth": max(a - num_slots, 0),
                                 "num_slots": num_slots,
                                 "draining": False,
                                 "phase": phase})
            elif self.path.startswith("/kv/"):
                rid = self.path[len("/kv/"):]
                with lock:
                    entry = adopted.get(rid)
                if entry is None:
                    self._send(404, {"error": "unknown"})
                    return
                if not entry["event"].wait(timeout=30.0):
                    self._send(504, {"error": "still decoding"})
                    return
                with lock:
                    adopted.pop(rid, None)
                self._send(200, {"result": entry["result"],
                                 "request_id": rid, "ttft_s": 0.0,
                                 "finish_reason": "length",
                                 "adopted": True})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if not self.path.startswith("/api/"):
                self._send(404, {"error": "not found"})
                return
            req = self._read()
            ids = [int(t) for t in req["input_text"].split()]
            n = int(req.get("max_new_tokens") or default_new_tokens)
            rid = req.get("request_id")
            push_to = req.get("disagg_push_to")
            with lock:
                active[0] += 1
            try:
                cost = len(ids) * prefill_per_tok_s
                if phase == "both":
                    # interference: this prefill preempted a running
                    # decode batch — the cost disaggregation removes
                    cost += switch_s
                with chip:
                    time.sleep(cost)
                if push_to:
                    if _fake_push(push_to, rid, ids, n):
                        with lock:
                            counters["redirects"] += 1
                        self._send(200, {"disagg_redirect": True,
                                         "request_id": rid,
                                         "target": push_to})
                        return
                    with lock:
                        counters["fallbacks"] += 1
                decode_sleep(n)
                self._send(200, {"result": _fake_result(ids, n),
                                 "request_id": rid, "ttft_s": 0.0,
                                 "finish_reason": "length"})
            finally:
                with lock:
                    active[0] -= 1

        def do_PUT(self):
            if not self.path.startswith("/kv/"):
                self._send(404, {"error": "not found"})
                return
            rid = self.path[len("/kv/"):]
            payload = self._read()
            if decline or phase == "prefill":
                with lock:
                    counters["declined"] += 1
                self._send(409, {"adopted": False,
                                 "reason": "injected" if decline
                                 else "wrong_phase"})
                return
            entry = {"event": threading.Event(), "result": None}
            with lock:
                adopted[rid] = entry
                counters["adopted"] += 1

            def run():
                decode_sleep(int(payload["n"]))
                entry["result"] = _fake_result(
                    [int(t) for t in payload["ids"]],
                    int(payload["n"]))
                entry["event"].set()

            threading.Thread(target=run, daemon=True).start()
            self._send(200, {"adopted": True, "request_id": rid})

        def do_DELETE(self):
            if not self.path.startswith("/kv/"):
                self._send(404, {"error": "not found"})
                return
            rid = self.path[len("/kv/"):]
            with lock:
                cancelled = adopted.pop(rid, None) is not None
            self._send(200, {"cancelled": cancelled})

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread, counters


def _start_fake_fleet(phases: List[str], slots: int, token_s: float,
                      prefill_per_tok_s: float, switch_s: float,
                      new_tokens: int, decline_decode: bool = False
                      ) -> Tuple[List[str], list, List[dict]]:
    targets, servers, counters = [], [], []
    for phase in phases:
        server, _t, c = start_fake_phase_replica(
            phase, slots, token_s, prefill_per_tok_s, switch_s,
            new_tokens,
            decline=(decline_decode and phase == "decode"))
        servers.append(server)
        counters.append(c)
        targets.append("127.0.0.1:%d" % server.server_address[1])
    return targets, servers, counters


def _stop_fakes(servers) -> None:
    for server in servers:
        try:
            server.shutdown()
            server.server_close()
        except OSError:
            pass


# ---- real replica subprocess (`--replica --phase X`) ----------------

def replica_main(port: int, phase: str) -> None:
    """Subprocess entry: the fleet bench's random-init llama replica
    plus a `DisaggCoordinator` and a configured serving phase — a
    faithful prefill- or decode-tier member."""
    import jax
    import jax.numpy as jnp

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       _start_warmup_thread,
                                       build_stdlib_server,
                                       create_continuous_engine,
                                       install_drain_handler)
    from fengshen_tpu.disagg.coordinator import DisaggCoordinator
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.pipelines.text_generation import Pipeline

    buckets = _buckets()
    new_tokens = _env("NEW_TOKENS", 16)
    config = LlamaConfig(
        vocab_size=_env("VOCAB", 4096),
        hidden_size=_env("HIDDEN", 1024),
        intermediate_size=_env("INTER", 2816),
        num_hidden_layers=_env("LAYERS", 4),
        num_attention_heads=_env("HEADS", 8),
        max_position_embeddings=buckets[-1] + new_tokens,
        dtype="float32")
    model = LlamaForCausalLM(config)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(_env("SEED", 0)))
    pipe = Pipeline(module=model, params=params,
                    tokenizer=_IntTokenizer(),
                    max_new_tokens=new_tokens, eos_token_id=None,
                    pad_token_id=0)
    engine = create_continuous_engine(
        pipe, {"num_slots": _env("SLOTS", 2), "buckets": buckets,
               "max_new_tokens": new_tokens, "max_queue": 512})
    disagg = DisaggCoordinator(engine, pipe)
    server_cfg = ServerConfig(host="127.0.0.1", port=port,
                              engine="continuous", phase=phase)
    pipeline_cfg = PipelineConfig(task="text_generation")
    ready = _start_warmup_thread(server_cfg, pipeline_cfg, pipe, engine)
    draining = threading.Event()
    server = build_stdlib_server(server_cfg, pipeline_cfg,
                                 pipeline=pipe, engine=engine,
                                 ready=ready, draining=draining,
                                 disagg=disagg)
    install_drain_handler(server, draining, engine=engine)
    print(f"[disagg-bench] {phase} replica on 127.0.0.1:{port}",
          flush=True)
    server.serve_forever()


def _spawn_real_replicas(phases: List[str], base_port: int
                         ) -> Tuple[List[str], list]:
    procs, targets = [], []
    for i, phase in enumerate(phases):
        port = base_port + i
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "fengshen_tpu.disagg.bench",
             "--replica", "--port", str(port), "--phase", phase]))
        targets.append(f"127.0.0.1:{port}")
    return targets, procs


# ---- the driver -----------------------------------------------------

def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.disagg.bench")
    parser.add_argument("--replica", action="store_true",
                        help="run as a bench replica subprocess")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--phase", type=str, default="both")
    args = parser.parse_args(argv)
    if args.replica:
        replica_main(args.port, args.phase)
        return

    n_prefill = _env("PREFILL", 2)
    n_decode = _env("DECODE", 2)
    n_homog = _env("HOMOGENEOUS", 3)
    slots = _env("SLOTS", 4)
    new_tokens = _env("NEW_TOKENS", 8)       # short decode tails …
    prompt_len = _env("PROMPT_LEN", 32)      # … behind long prompts
    n_req = max(_env("REQUESTS", 24), 2)
    fake = _env("FAKE", 0) == 1
    token_s = _fenv("FAKE_TOKEN_S", 0.005)
    prefill_per_tok_s = _fenv("FAKE_PREFILL_S", 0.001)
    switch_s = _fenv("FAKE_SWITCH_S", 0.05)
    width = max(2 * (n_prefill + n_decode) * slots, 8)

    import random as _random
    rng = _random.Random(_env("SEED", 0))
    prompts = [" ".join(str(rng.randint(3, 95))
                        for _ in range(prompt_len))
               for _ in range(n_req)]

    disagg_phases = (["prefill"] * n_prefill
                     + ["decode"] * n_decode)
    topology = f"prefill={n_prefill},decode={n_decode}"

    all_servers: list = []
    procs: list = []
    try:
        # 1. homogeneous baseline: N both-phase replicas
        if fake:
            h_targets, h_servers, _ = _start_fake_fleet(
                ["both"] * n_homog, slots, token_s,
                prefill_per_tok_s, switch_s, new_tokens)
            all_servers += h_servers
        else:
            h_targets, h_procs = _spawn_real_replicas(
                ["both"] * n_homog, _env("BASE_PORT", 8260))
            procs += h_procs
        rh = _make_router(h_targets)
        homog = _drive(rh, prompts, new_tokens, width=width)
        rh.stop()
        if fake:
            _stop_fakes(h_servers)

        # 2. disaggregated: prefill tier + decode tier, REAL router
        #    placement + KV push + redirect/collect end to end
        if fake:
            d_targets, d_servers, d_counters = _start_fake_fleet(
                disagg_phases, slots, token_s, prefill_per_tok_s,
                switch_s, new_tokens)
            all_servers += d_servers
        else:
            d_targets, d_procs = _spawn_real_replicas(
                disagg_phases, _env("BASE_PORT", 8260) + n_homog)
            procs += d_procs
        rd = _make_router(d_targets)
        disagg = _drive(rd, prompts, new_tokens, width=width)
        state = rd.fleet_state()
        rd.stop()
        if fake:
            _stop_fakes(d_servers)
            redirects = sum(c["redirects"] for c in d_counters)
        else:
            redirects = None

        # 3. fallback rung (fake lane): decode tier declines every
        #    adoption — zero client-visible errors allowed
        fallback_section = {"enabled": False}
        if fake:
            f_targets, f_servers, f_counters = _start_fake_fleet(
                disagg_phases, slots, token_s, prefill_per_tok_s,
                switch_s, new_tokens, decline_decode=True)
            all_servers += f_servers
            rf = _make_router(f_targets)
            fb = _drive(rf, prompts, new_tokens, width=width)
            rf.stop()
            _stop_fakes(f_servers)
            fallback_section = {
                "enabled": True,
                "failed": len(fb["failed"]),
                "completed": sum(1 for r in fb["results"]
                                 if r is not None),
                "fallbacks": sum(c["fallbacks"] for c in f_counters),
                "declined": sum(c["declined"] for c in f_counters),
                "token_identical": fb["results"] == homog["results"],
            }

        tps_h = homog["tokens_per_sec"]
        tps_d = disagg["tokens_per_sec"]
        if fake:
            backend = "fake"
        else:
            import jax
            backend = jax.default_backend()
        _emit({
            "metric": "disagg_tokens_per_sec",
            "value": round(tps_d, 1),
            "unit": "tokens/s",
            "vs_baseline": round(tps_d / tps_h, 3) if tps_h > 0
            else 0.0,
            "mode": "disagg",
            # the comparison identity: benchdiff never compares rows
            # across replica counts OR phase topologies
            "replicas": n_prefill + n_decode,
            "topology": topology,
            "router_topology": state.get("topology"),
            "homogeneous_replicas": n_homog,
            "tokens_per_sec_homogeneous": round(tps_h, 1),
            "num_slots": slots,
            "requests": n_req,
            "new_tokens": new_tokens,
            "prompt_len": prompt_len,
            "failed": len(homog["failed"]) + len(disagg["failed"]),
            "redirects": redirects,
            "token_identical_disagg_vs_homogeneous":
                disagg["results"] == homog["results"],
            "fallback": fallback_section,
            "fake": fake,
            "backend": backend,
        })
    finally:
        _stop_fakes(all_servers)
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


if __name__ == "__main__":
    main()
