"""Phase-aware placement policy for prefill/decode disaggregation.

Pure stdlib, NO jax — this module runs inside the fleet router process
(`fleet/router.py` consults it per placement), and the fleet package's
no-jax contract (pinned by subprocess test) extends to everything the
router imports.

Replicas advertise a `phase` in `/stats` (`prefill` | `decode` |
`both`, from the server config's `--phases` spawn flag):

- ``prefill`` tiers take admissions, prime the lane, and push the KV
  prefix to a decode peer;
- ``decode`` tiers adopt pushed lanes and run the long decode tail;
- ``both`` (the default) is the homogeneous mode — a fleet with no
  phase split routes exactly as before this module existed.

`plan_handoff` returns a (prefill, decode) pair only when the fleet
actually has BOTH tiers healthy; every degenerate topology (all-both,
prefill-only, decode-only) returns None and the router falls back to
plain least-occupancy placement — disaggregation is an optimization,
never a new way to fail a request.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

#: the valid replica phase labels, in docs order
PHASES = ("prefill", "decode", "both")


def validate_phase(phase: str) -> str:
    """Normalize + reject unknown phase labels (config-load guard)."""
    p = str(phase or "both").strip().lower()
    if p not in PHASES:
        raise ValueError(
            f"unknown replica phase {phase!r}; expected one of {PHASES}")
    return p


@dataclasses.dataclass(frozen=True)
class HandoffPlan:
    """One placement decision: prime on `prefill`, decode on `decode`.
    The fields are the router's replica records (duck-typed: anything
    with `phase` and `occupancy()`)."""
    prefill: Any
    decode: Any


def _least_occupied(replicas: Sequence[Any]) -> Optional[Any]:
    best = None
    best_occ = None
    for rep in replicas:
        occ = rep.occupancy()
        if best is None or occ < best_occ:
            best, best_occ = rep, occ
    return best


def plan_handoff(candidates: Sequence[Any]) -> Optional[HandoffPlan]:
    """Pick the least-occupied prefill and decode replicas from the
    router's HEALTHY candidate list (ties by iteration order, which
    the router keeps index-sorted — deterministic placement).

    Returns None unless at least one healthy replica of EACH dedicated
    phase exists: a fleet mid-rollout (decode tier down, prefill tier
    up) must keep serving through the homogeneous path rather than
    pushing lanes nowhere.
    """
    prefills = [r for r in candidates if r.phase == "prefill"]
    decodes = [r for r in candidates if r.phase == "decode"]
    if not prefills or not decodes:
        return None
    return HandoffPlan(prefill=_least_occupied(prefills),
                       decode=_least_occupied(decodes))


def plan_evacuation(peers: Sequence[dict]) -> List[str]:
    """Rank evacuation targets for drain-time lane rescue
    (docs/fault_tolerance.md "Preemption runbook"). `peers` are probed
    `/stats` snapshots as plain dicts — at least ``url``, plus
    ``draining`` / ``phase`` / ``slots_active`` / ``num_slots`` /
    ``queue_depth`` when the probe answered (missing fields default
    safe). Returns peer urls best-first; the coordinator pushes each
    lane down the list until one adopts.

    Ordering: draining peers are excluded entirely (they are leaving
    too — an evacuated lane must not need a SECOND rescue seconds
    later); dedicated prefill tiers rank after decode/both replicas
    (an evacuated lane is mid-decode work); within a tier, least
    occupancy first with input order breaking ties — the same
    determinism contract as `plan_handoff`. An empty result means
    every lane finishes locally, never an error."""
    ranked = []
    for i, peer in enumerate(peers):
        if peer.get("draining"):
            continue
        phase = str(peer.get("phase") or "both")
        denom = max(int(peer.get("num_slots") or 0), 1)
        occ = (int(peer.get("slots_active") or 0)
               + int(peer.get("queue_depth") or 0)) / denom
        ranked.append((1 if phase == "prefill" else 0, occ, i,
                       str(peer["url"])))
    ranked.sort(key=lambda t: t[:3])
    return [url for _, _, _, url in ranked]


def topology(phases: Sequence[str]) -> str:
    """Canonical topology label for BENCH rows and `/fleet`:
    ``"homogeneous"`` when no replica declares a dedicated phase, else
    ``"prefill=P,decode=D"`` (with ``,both=B`` appended when mixed).
    `benchdiff._identity` folds this into the comparison key so
    disaggregated runs never diff against homogeneous ones.
    """
    counts = {p: 0 for p in PHASES}
    for p in phases:
        counts[validate_phase(p)] += 1
    if counts["prefill"] == 0 and counts["decode"] == 0:
        return "homogeneous"
    label = f"prefill={counts['prefill']},decode={counts['decode']}"
    if counts["both"]:
        label += f",both={counts['both']}"
    return label
