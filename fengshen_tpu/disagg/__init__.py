"""Prefill/decode disaggregation (docs/disaggregation.md).

Submodule map — import weight matters here because the fleet router
imports this package in its NO-JAX process:

- `transfer`: the stdlib HTTP push of an exported lane (checksum,
  size cap, timeout). No jax.
- `policy`: phase-aware placement (`plan_handoff`, `topology`) the
  fleet router consults per request. No jax.
- `coordinator`: the replica-side orchestration (export → push →
  detach, adopt → collect). Imports the serving engine, so it is NOT
  imported here — the api layer imports
  `fengshen_tpu.disagg.coordinator` explicitly.
- `bench`: the serve-bench-disagg harness (same split: imported by
  name only).
"""

from fengshen_tpu.disagg import policy, transfer
from fengshen_tpu.disagg.policy import (HandoffPlan, plan_handoff,
                                        topology, validate_phase)
from fengshen_tpu.disagg.transfer import (KvPushError, payload_checksum,
                                          push_payload, seal,
                                          verify_checksum)

__all__ = [
    "policy", "transfer", "HandoffPlan", "plan_handoff", "topology",
    "validate_phase", "KvPushError", "payload_checksum",
    "push_payload", "seal", "verify_checksum",
]
