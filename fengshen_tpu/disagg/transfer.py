"""KV-handoff transfer plane: push one sealed lane payload replica to
replica over plain HTTP.

Pure stdlib by design — this module rides in the SAME process as the
fleet router's placement policy (`disagg/policy.py`) and must never
drag jax into the router (the fleet package's no-jax subprocess test
extends to `fengshen_tpu.disagg`). The payload itself is built and
consumed by `fengshen_tpu.serving.handoff` on the replicas, which do
hold jax; here it is an opaque JSON dict.

Three integrity guards, all enforced on BOTH ends:

- ``checksum``: sha256 over the canonical JSON of the payload minus
  the checksum field (`seal()`/`verify_checksum()`), so a truncated or
  bit-flipped transfer is an adopt-decline, never a corrupted lane;
- ``max_bytes``: a size cap on the encoded payload (prefill replicas
  must not buffer unbounded lanes for a slow decode peer);
- ``timeout_s``: the push is a blocking host-side HTTP call on the
  coordinator thread — bounded, and any failure maps to ONE
  `KvPushError` with a `reason` the fallback counter can label.
"""

from __future__ import annotations

import hashlib
import json
import socket
import urllib.error
import urllib.request
from typing import Optional

#: default encoded-payload cap: generous for int8-quantized lanes of
#: the supported model sizes, small enough to bound coordinator memory
DEFAULT_MAX_PAYLOAD_BYTES = 256 * 1024 * 1024


class KvPushError(Exception):
    """One failed push attempt. `reason` is the fallback label
    (connect / timeout / too_large / adopt_declined / http_<status>);
    `sent` mirrors the fleet transport contract — False means the
    payload provably never reached the peer, True means it may have."""

    def __init__(self, message: str, reason: str, sent: bool = True):
        super().__init__(message)
        self.reason = reason
        self.sent = sent


def canonical_bytes(payload: dict) -> bytes:
    """Deterministic encoding of the payload WITHOUT its checksum
    field — the hashed representation and the size-cap denominator."""
    body = {k: v for k, v in payload.items() if k != "checksum"}
    return json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def payload_checksum(payload: dict) -> str:
    return hashlib.sha256(canonical_bytes(payload)).hexdigest()


def payload_nbytes(payload: dict) -> int:
    return len(canonical_bytes(payload))


def seal(payload: dict) -> dict:
    """Stamp the checksum onto a freshly exported payload (in place,
    and returned for chaining)."""
    payload["checksum"] = payload_checksum(payload)
    return payload


def verify_checksum(payload: dict) -> bool:
    want = payload.get("checksum")
    return isinstance(want, str) and payload_checksum(payload) == want


def push_payload(base_url: str, request_id: str, payload: dict,
                 timeout_s: float = 10.0,
                 max_bytes: int = DEFAULT_MAX_PAYLOAD_BYTES,
                 transport=None) -> dict:
    """PUT the sealed payload to ``<base_url>/kv/<request_id>`` and
    return the peer's adopt-ack body. Raises `KvPushError` on every
    failure mode; never raises anything else.

    `transport` optionally substitutes a fleet-style
    ``request(base_url, method, path, body, timeout_s)`` callable —
    the seam the fault-injection tests wedge/kill the push through.
    """
    nbytes = payload_nbytes(payload)
    if nbytes > max_bytes:
        raise KvPushError(
            f"payload of {nbytes} bytes exceeds the transfer cap "
            f"{max_bytes}", reason="too_large", sent=False)
    path = f"/kv/{request_id}"
    if transport is not None:
        try:
            status, body = transport.request(
                base_url, "PUT", path, body=payload, timeout_s=timeout_s)
        except Exception as e:  # noqa: BLE001 — transport failures
            # collapse to the one typed error the fallback path labels
            sent = bool(getattr(e, "sent", True))
            reason = "connect" if not sent else "timeout"
            raise KvPushError(str(e), reason=reason, sent=sent) from e
        return _check_ack(status, body)
    data = json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        base_url.rstrip("/") + path, data=data, method="PUT",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return _check_ack(resp.status,
                              json.loads(resp.read().decode("utf-8")))
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 — non-JSON error body
            body = {"error": str(e)}
        return _check_ack(e.code, body)
    except (socket.timeout, TimeoutError) as e:
        raise KvPushError(f"push timed out after {timeout_s}s",
                          reason="timeout", sent=True) from e
    except (urllib.error.URLError, ConnectionError, OSError) as e:
        raise KvPushError(f"push failed: {e}", reason="connect",
                          sent=False) from e


def _check_ack(status: int, body: dict) -> dict:
    """Adopt-ack contract: 200 + ``{"adopted": true}`` is the ONLY
    success. A well-formed decline (any status with an ``adopted``
    field) carries the peer's reason; anything else is transport
    noise."""
    body = body if isinstance(body, dict) else {}
    if status == 200 and body.get("adopted") is True:
        return body
    if "adopted" in body:
        raise KvPushError(
            f"peer declined adoption: {body.get('reason', 'unknown')}",
            reason="adopt_declined", sent=True)
    raise KvPushError(f"push got HTTP {status}",
                      reason=f"http_{status}", sent=True)
