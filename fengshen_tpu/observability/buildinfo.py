"""Startup/warmup telemetry: the build-info gauge and the warmup-phase
gauge every entry point records into the global registry.

Before this, warmup seconds only appeared in stdout logs — a scraper
could not answer "how long did this replica take to become ready" or
"which jax build is this fleet actually running". Now:

- ``fstpu_build_info{jax_version,backend}`` is a constant ``1``
  info-gauge (the Prometheus idiom: the VALUE is meaningless, the
  labels are the payload) set by the api server, the trainer, and the
  AOT CLI at startup;
- ``fstpu_warmup_seconds{phase}`` records each warmup phase's wall
  seconds: ``engine`` (serving engine compile of all prefill buckets +
  decode), ``pipeline`` (the legacy batch-1 warmup request), and
  ``aot_replay`` (manifest-driven pre-compilation, see
  docs/aot_cache.md).

Pure-stdlib except for the lazy jax probe, which degrades to
``jax_version="none"`` so the exporter works on hosts without jax.
"""

from __future__ import annotations

from typing import Optional

from fengshen_tpu.observability.registry import (MetricsRegistry,
                                                 get_registry)

BUILD_INFO_METRIC = "fstpu_build_info"
WARMUP_METRIC = "fstpu_warmup_seconds"


def record_build_info(registry: Optional[MetricsRegistry] = None) -> None:
    """Set the constant info-gauge for this process's jax build."""
    try:
        import jax
        version, backend = jax.__version__, jax.default_backend()
    except Exception:  # noqa: BLE001 — no/broken jax: still expose
        # SOMETHING a scraper can alert on
        version, backend = "none", "none"
    reg = registry if registry is not None else get_registry()
    reg.gauge(
        BUILD_INFO_METRIC,
        "constant 1; jax build + backend as labels",
        labelnames=("jax_version", "backend"),
    ).labels(version, backend).set(1)


def record_warmup_seconds(phase: str, seconds: float,
                          registry: Optional[MetricsRegistry] = None
                          ) -> None:
    """Record one warmup phase's wall seconds (gauge: the LAST warmup
    of each phase is the replica's current cold-start cost)."""
    reg = registry if registry is not None else get_registry()
    reg.gauge(
        WARMUP_METRIC,
        "wall seconds of each startup warmup phase",
        labelnames=("phase",),
    ).labels(phase).set(float(seconds))
